"""Standalone interconnect benchmark — the ic_bench / udp2 analog.

The reference ships a kernel-independent interconnect benchmark
(contrib/interconnect/test/ic_bench.c, contrib/udp2's standalone-testable
transport): measure the motion layer WITHOUT the executor on top. Here the
motion layer is XLA collectives over the segment mesh, so this tool times
exactly the three collectives the engine's motions lower to
(exec/dist_executor.py):

- GATHER / BROADCAST  -> all_gather
- HASH redistribute   -> all_to_all
- check reduction     -> psum

Two modes:

- primitive (default): raw collective bandwidth per payload size.
- motion (``--format packed|percol|both``): a full TPC-H-shaped hash
  SHUFFLE through the engine's real motion lowering
  (exec/dist_executor.py DistLowerer._redistribute) — ``packed`` ships
  every column plus the validity mask in ONE fused all_to_all on the
  wire format of exec/kernels.py, sized to the adaptive capacity rung
  the ladder converges to; ``percol`` replays the legacy one-collective-
  per-column launches over planner-worst-case buckets. Reports launches
  (counted at trace time), bytes-on-wire, padding efficiency, and wall
  time; ``both`` additionally cross-checks per-column checksums between
  the formats.

Runs on whatever mesh is visible: 8 virtual CPU devices (tests), a real
TPU slice, or a multi-host cluster joined via mesh.init_distributed
(CBTPU_* env). Prints one JSON line per measurement; ``--csv`` appends
the same rows to a CSV file.

A third mode (``--two-level``) A/Bs the flat vs HIERARCHICAL shuffle at
a simulated multi-host split (CBTPU_FORCE_HOSTS env-forced process
grouping on CPU): per format the analytic DCN/ICI byte split, launches,
wall time, and exact checksum parity — the two-level transport's
received buffers are bit-identical to flat by construction.

Usage: python -m tools.ic_bench [--segs N] [--sizes bytes,...]
       python -m tools.ic_bench --format packed [--rows N] [--cols 10]
                                [--skew 0.5] [--csv out.csv]
       python -m tools.ic_bench --two-level --hosts 4 [--csv out.csv]
"""

from __future__ import annotations

import argparse
import json
import os
import time

import numpy as np


class CountingTransport:
    """Transport proxy counting data-plane collective launches at trace
    time (all_gather / all_to_all; the stats pmax and check psum are
    control-plane and excluded from the launch comparison)."""

    def __init__(self, inner):
        self.inner = inner
        self.launches = 0

    def all_gather(self, x, axis):
        self.launches += 1
        return self.inner.all_gather(x, axis)

    def all_to_all(self, x, axis):
        self.launches += 1
        return self.inner.all_to_all(x, axis)

    def psum(self, x, axis):
        return self.inner.psum(x, axis)

    def pmax(self, x, axis):
        return self.inner.pmax(x, axis)


def shuffle_columns(n_cols: int, rows: int, nseg: int, skew: float,
                    seed: int = 11, src_skew: bool = False) -> dict:
    """A TPC-H-shaped wide row set: int64 keys/amounts (DECIMAL cents ride
    int64), f64 prices, int32 dates, an f32 and a bool flag — ``n_cols``
    columns per segment, (nseg, rows) each. Column "c0" is the hash key;
    ``skew`` is the fraction of rows sharing ONE hot key. ``src_skew``
    concentrates the hot rows on SOURCE segment 0 (the one-shard-holds-
    the-hot-slice shape of time-ordered ingest) — the case where flat
    motion pads EVERY source segment's buckets to the hot shard's
    demand while the two-level exchange pads per host pair."""
    rng = np.random.default_rng(seed)
    cols: dict[str, np.ndarray] = {}
    kinds = ["i64", "i64", "f64", "i32", "i64", "f64", "i32", "f32",
             "bool", "i64"]
    for i in range(n_cols):
        kind = kinds[i % len(kinds)]
        if i == 0:
            k = rng.integers(0, 100_000, (nseg, rows))
            hot = rng.random((nseg, rows)) < skew
            if src_skew:
                hot &= (np.arange(nseg) == 0)[:, None]
            cols["c0"] = np.where(hot, 7, k).astype(np.int64)
        elif kind == "i64":
            cols[f"c{i}"] = rng.integers(-1 << 40, 1 << 40, (nseg, rows))
        elif kind == "f64":
            cols[f"c{i}"] = rng.standard_normal((nseg, rows))
        elif kind == "i32":
            cols[f"c{i}"] = rng.integers(0, 20_000, (nseg, rows)
                                         ).astype(np.int32)
        elif kind == "f32":
            cols[f"c{i}"] = rng.standard_normal(
                (nseg, rows)).astype(np.float32)
        else:
            cols[f"c{i}"] = rng.integers(0, 2, (nseg, rows)
                                         ).astype(np.bool_)
    return cols


def bench_shuffle(fmt: str, nseg: int, rows: int, n_cols: int,
                  skew: float, backend: str, reps: int,
                  capacity_factor: float = 2.0) -> dict:
    """One shuffle measurement through the engine's real motion lowering;
    returns the JSON record (and the received checksums under "_sums"
    for the both-formats parity check)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from cloudberry_tpu.exec import kernels as K
    from cloudberry_tpu.exec.dist_executor import DistLowerer, _shard_map
    from cloudberry_tpu.parallel.mesh import SEG_AXIS, segment_mesh
    from cloudberry_tpu.parallel.transport import make_transport
    from cloudberry_tpu.plan import expr as ex
    from cloudberry_tpu.plan import nodes as N
    from cloudberry_tpu.types import INT64
    from cloudberry_tpu.utils import hashing

    mesh = segment_mesh(nseg)
    data = shuffle_columns(n_cols, rows, nseg, skew)
    packed = fmt == "packed"

    # bucket capacity: percol replays the static planner discipline
    # (fair share × capacity_factor); packed sizes to the adaptive rung
    # the ladder converges to — the actual global max bucket, rounded up
    dest_all = hashing.jump_consistent_hash_np(
        hashing.hash_columns_np([data["c0"].reshape(-1)]), nseg)
    actual_max = int(np.bincount(
        np.repeat(np.arange(nseg), rows) * nseg + dest_all,
        minlength=nseg * nseg).max())
    if packed:
        bucket_cap = K.rung_up(actual_max)
    else:
        bucket_cap = max(int(np.ceil(rows / nseg * capacity_factor)), 8)
        bucket_cap = max(bucket_cap, actual_max)  # complete, not error

    node = N.PMotion(None, "redistribute",
                     hash_keys=[ex.ColumnRef("c0", INT64)])
    node.bucket_cap = bucket_cap

    tx = CountingTransport(make_transport(backend, nseg))

    def _cksum(v, osel):
        # order-independent exact checksum: sum of the value's u32 words
        # over selected rows, in uint64 (no float reduction-order noise —
        # the packed/percol parity comparison must be exact)
        if v.dtype == jnp.bool_:
            w = v.astype(jnp.uint32)[..., None]
        else:
            w = jax.lax.bitcast_convert_type(v, jnp.uint32)
            if w.ndim == v.ndim:
                w = w[..., None]
        return jnp.sum(jnp.where(osel[..., None], w,
                                 jnp.uint32(0)).astype(jnp.uint64))

    def seg_fn(x):
        cols = {k: v[0] for k, v in x.items()}
        sel = jnp.ones((rows,), dtype=jnp.bool_)
        low = DistLowerer({}, nseg, tx=tx, packed=packed)
        out, osel = low._redistribute(node, cols, sel)
        # checksums keep every received column alive (and cross-check
        # packed vs percol when both formats run)
        return {k: _cksum(v, osel)[None] for k, v in out.items()}

    in_specs = ({k: P(SEG_AXIS, None) for k in data},)
    fn = jax.jit(_shard_map(seg_fn, mesh, in_specs, P(SEG_AXIS)))
    out = jax.block_until_ready(fn(data))  # trace + compile (counts tx)
    best = float("inf")
    for _ in range(reps):
        t0 = time.time()
        out = jax.block_until_ready(fn(data))
        best = min(best, time.time() - t0)

    layout = K.wire_layout({k: jnp.asarray(v[0]).dtype
                            for k, v in data.items()})
    n_bufrows = nseg * bucket_cap
    if packed:
        wire = n_bufrows * layout.row_bytes()
    else:
        wire = sum(n_bufrows * np.dtype(v.dtype).itemsize
                   for v in data.values()) + n_bufrows  # + bool sel buffer
    payload = rows * layout.payload_bytes()  # rows actually routed
    rec = {
        "mode": "shuffle",
        "format": fmt,
        "backend": backend,
        "n_segments": nseg,
        "rows_per_seg": rows,
        "n_cols": n_cols,
        "skew": skew,
        "bucket_cap": bucket_cap,
        "collective_launches": tx.launches,
        "wire_bytes_per_seg": int(wire),
        "payload_bytes_per_seg": int(payload),
        "padding_frac": round(1.0 - payload / wire, 4),
        "wall_ms": round(best * 1e3, 3),
        "gbps_per_seg": round(wire * 8 / best / 1e9, 3),
    }
    # keep exact uint64 checksums (a float() here would collapse low-bit
    # differences past 2^53 and mask real corruption in the parity check)
    rec["_sums"] = {k: int(np.asarray(v).sum(dtype=np.uint64))
                    for k, v in out.items()}
    return rec


def bench_two_level(nseg: int, hosts: int, rows: int, n_cols: int,
                    skew: float, reps: int,
                    csv_path: str | None) -> None:
    """Flat vs hierarchical shuffle A/B at a SIMULATED multi-host split
    (CBTPU_FORCE_HOSTS partitions the single-process mesh into
    contiguous uniform hosts — the env-forced process grouping). Both
    formats run the engine's real motion lowering; the hierarchical run
    carries the planner-style host stamps and the two-level transport.
    Reports per format the analytic DCN/ICI byte split (flat: every
    cross-host segment-pair block crosses DCN padded to the pair rung;
    two-level: one aggregated block per host pair at the host rung,
    with the lane staging hops riding ICI), collective launches counted
    at trace time, wall clock, and exact per-column checksum parity —
    the received buffers are bit-identical by construction, and the
    parity record proves it."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P

    from cloudberry_tpu.config import Config
    from cloudberry_tpu.exec import kernels as K
    from cloudberry_tpu.exec.dist_executor import DistLowerer, _shard_map
    from cloudberry_tpu.parallel.mesh import SEG_AXIS, segment_mesh
    from cloudberry_tpu.parallel.transport import (flat_wire_model,
                                                   hier_topology,
                                                   make_transport,
                                                   two_level_wire_model)
    from cloudberry_tpu.plan import expr as ex
    from cloudberry_tpu.plan import nodes as N
    from cloudberry_tpu.types import INT64
    from cloudberry_tpu.utils import hashing

    if nseg % hosts:
        raise SystemExit(f"--hosts {hosts} must divide --segs {nseg}")
    os.environ["CBTPU_FORCE_HOSTS"] = str(hosts)
    S = nseg // hosts
    mesh = segment_mesh(nseg)
    data = shuffle_columns(n_cols, rows, nseg, skew, src_skew=True)

    # adaptive rungs from the ACTUAL demand (the state the capacity
    # ladder converges to), at both granularities
    dest_all = hashing.jump_consistent_hash_np(
        hashing.hash_columns_np([data["c0"].reshape(-1)]), nseg)
    src_all = np.repeat(np.arange(nseg), rows)
    B = K.rung_up(int(np.bincount(
        src_all * nseg + dest_all, minlength=nseg * nseg).max()))
    HB = K.rung_up(int(np.bincount(
        (src_all // S) * hosts + dest_all // S,
        minlength=hosts * hosts).max()))

    layout = K.wire_layout({k: jnp.asarray(v[0]).dtype
                            for k, v in data.items()})
    rb = layout.row_bytes()
    cfg = Config(n_segments=nseg).with_overrides(
        **{"interconnect.hierarchical": "on"})

    def _cksum(v, osel):
        if v.dtype == jnp.bool_:
            w = v.astype(jnp.uint32)[..., None]
        else:
            w = jax.lax.bitcast_convert_type(v, jnp.uint32)
            if w.ndim == v.ndim:
                w = w[..., None]
        return jnp.sum(jnp.where(osel[..., None], w,
                                 jnp.uint32(0)).astype(jnp.uint64))

    recs = {}
    for fmt in ("flat", "hier"):
        node = N.PMotion(None, "redistribute",
                         hash_keys=[ex.ColumnRef("c0", INT64)])
        node.bucket_cap = B
        if fmt == "hier":
            node.host_bucket_cap = HB
            node.hier_hosts = hosts
            tx = make_transport("xla", nseg,
                                topo=hier_topology(cfg, nseg))
        else:
            tx = CountingTransport(make_transport("xla", nseg))

        def seg_fn(x):
            cols = {k: v[0] for k, v in x.items()}
            sel = jnp.ones((rows,), dtype=jnp.bool_)
            low = DistLowerer({}, nseg, tx=tx, packed=True)
            out, osel = low._redistribute(node, cols, sel)
            return {k: _cksum(v, osel)[None] for k, v in out.items()}

        in_specs = ({k: P(SEG_AXIS, None) for k in data},)
        fn = jax.jit(_shard_map(seg_fn, mesh, in_specs, P(SEG_AXIS)))
        out = jax.block_until_ready(fn(data))   # trace counts launches
        best = float("inf")
        for _ in range(reps):
            t0 = time.time()
            out = jax.block_until_ready(fn(data))
            best = min(best, time.time() - t0)

        launches = tx.launches
        if fmt == "hier":
            model = two_level_wire_model(nseg, hosts, B, HB, rb)
        else:
            model = flat_wire_model(nseg, hosts, B, rb)
        dcn, ici = model["dcn_bytes"], model["ici_bytes"]
        rec = {
            "mode": "two-level",
            "format": fmt,
            "hosts": hosts,
            "n_segments": nseg,
            "rows_per_seg": rows,
            "n_cols": n_cols,
            "skew": skew,
            "bucket_cap": B,
            "host_bucket_cap": HB if fmt == "hier" else 0,
            "launches": launches,
            "dcn_bytes": int(dcn),
            "ici_bytes": int(ici),
            "wall_ms": round(best * 1e3, 3),
        }
        rec["_sums"] = {k: int(np.asarray(v).sum(dtype=np.uint64))
                        for k, v in out.items()}
        recs[fmt] = rec
        _emit(rec, csv_path)
    a, b = recs["flat"]["_sums"], recs["hier"]["_sums"]
    ok = set(a) == set(b) and all(a[k] == b[k] for k in a)
    _emit({
        "mode": "two-level-summary",
        "hosts": hosts,
        "checksums_match": bool(ok),
        "dcn_ratio": round(recs["flat"]["dcn_bytes"]
                           / max(recs["hier"]["dcn_bytes"], 1), 3),
        "ici_ratio": round(recs["flat"]["ici_bytes"]
                           / max(recs["hier"]["ici_bytes"], 1), 3),
        "launch_delta": recs["hier"]["launches"]
        - recs["flat"]["launches"],
    }, csv_path)
    if not ok:
        raise SystemExit("two-level checksum parity FAILED")


def bench_join_filter(nseg: int, rows: int, dim_rows: int, skew: float,
                      reps: int, csv_path: str | None) -> None:
    """Engine-level PK–FK shuffle with the DIGEST runtime filter on vs
    off (the semijoin-reduction measurement): a skewed fact table joins a
    dimension covering only a fraction of the key domain, so most probe
    rows provably have no partner. Reports — per mode — the probe rows
    actually shipped (the filter's psum'd pre/post stats), the capacity
    rung the redistribute seeded, wire bytes at that rung, and wall time;
    then a repeated-statement microbench showing the join-index cache
    (cache-hit counter, compile delta — the no-argsort/no-recompile
    acceptance)."""
    import time as _t

    import cloudberry_tpu as cb
    from cloudberry_tpu.config import Config
    from cloudberry_tpu.exec import kernels as K
    from cloudberry_tpu.plan import nodes as PN
    from cloudberry_tpu.plan.binder import Binder
    from cloudberry_tpu.plan.planner import _optimize
    from cloudberry_tpu.sql.parser import parse_sql

    rng = np.random.default_rng(17)
    # fact keys: skew fraction lands on ONE hot key OUTSIDE the dim
    # domain (dim covers [0, dim_rows), fact spans 10x that), the rest
    # uniform — so the filter both drops ~90% of the uniform probes AND
    # deletes the hot bucket that sized the unfiltered capacity rung:
    # semijoin reduction doubles as skew relief, the MPP classic
    ks = rng.integers(0, dim_rows * 10, rows)
    hot = rng.random(rows) < skew
    grp = np.where(hot, dim_rows * 5, ks)

    def mk(enabled: bool):
        cfg = Config(n_segments=nseg).with_overrides(**{
            "planner.broadcast_threshold": 0,       # force redistribute
            "planner.runtime_filter_threshold": 0,  # digest, never exact
            "join_filter.enabled": enabled,
            "join_filter.bloom_bits": 1 << 14,
        })
        s = cb.Session(cfg)
        s.sql("create table fact (k bigint, grp bigint, v bigint) "
              "distributed by (k)")
        s.sql("create table dim (d bigint, p bigint) distributed by (d)")
        vals = ",".join(f"({i}, {int(g)}, {i % 97})"
                        for i, g in enumerate(grp))
        s.sql(f"insert into fact values {vals}")
        vals = ",".join(f"({i}, {i * 2})" for i in range(dim_rows))
        s.sql(f"insert into dim values {vals}")
        return s

    q = ("select grp, count(*) as n from fact, dim where grp = d "
         "group by grp order by grp")
    recs = {}
    for enabled in (False, True):
        s = mk(enabled)
        plan = _optimize(Binder(s.catalog, s.config)
                         .bind_query(parse_sql(q)), s)
        probe_motion = next(
            m for m in _walk(plan, PN.PMotion)
            if m.kind == "redistribute"
            and any(sc.table_name == "fact" for sc in _walk(m, PN.PScan)))
        layout = K.wire_layout({f.name: f.type.np_dtype
                                for f in probe_motion.fields})
        s.sql(q)  # warm (compile + first stats)
        best = float("inf")
        for _ in range(reps):
            t0 = _t.time()
            s.sql(q)
            best = min(best, _t.time() - t0)
        runs = 1 + reps
        # jf_rows_in == 0 means the cost model declined to insert any
        # filter: report the unfiltered row count, not a perfect 0
        fired = enabled and s.stmt_log.counter("jf_rows_in") > 0
        shipped = (s.stmt_log.counter("jf_rows_out") // runs
                   if fired else rows)
        rec = {
            "mode": "join_filter",
            "filter": "on" if enabled else "off",
            "n_segments": nseg,
            "fact_rows": rows,
            "dim_rows": dim_rows,
            "skew": skew,
            "probe_rows_shipped": int(shipped),
            "bucket_rung": int(probe_motion.bucket_cap),
            "wire_bytes_per_seg": int(probe_motion.bucket_cap * nseg
                                      * layout.row_bytes()),
            "wall_ms": round(best * 1e3, 3),
        }
        recs[enabled] = (rec, s)
        _emit(rec, csv_path)
    off, on = recs[False][0], recs[True][0]
    s_on = recs[True][1]
    c0 = s_on.stmt_log.counter("compiles")
    h0 = s_on.stmt_log.counter("join_index_hits")
    s_on.sql(q)
    s_on.sql(q)
    _emit({
        "mode": "join_filter-summary",
        "row_reduction": round(1.0 - on["probe_rows_shipped"]
                               / max(off["probe_rows_shipped"], 1), 4),
        "wire_bytes_reduction": round(1.0 - on["wire_bytes_per_seg"]
                                      / max(off["wire_bytes_per_seg"], 1),
                                      4),
        "rung_ratio": round(off["bucket_rung"]
                            / max(on["bucket_rung"], 1), 2),
        # repeated-statement microbench: the sorted-build cache serves
        # the dim argsort from the session LRU with ZERO recompiles
        "join_index_hits": s_on.stmt_log.counter("join_index_hits") - h0,
        "repeat_compiles": s_on.stmt_log.counter("compiles") - c0,
    }, csv_path)


def _walk(plan, kind):
    from cloudberry_tpu.exec.executor import all_nodes

    seen = set()
    out = []
    for n in all_nodes(plan):
        if isinstance(n, kind) and id(n) not in seen:
            seen.add(id(n))
            out.append(n)
    return out


def _emit(rec: dict, csv_path: str | None) -> None:
    sums = rec.pop("_sums", None)
    print(json.dumps(rec), flush=True)
    if csv_path:
        import csv
        import sys

        fields = list(rec)
        path = csv_path
        if os.path.exists(path):
            with open(path, newline="") as f:
                header = f.readline().strip().split(",")
            if header != fields:
                # primitive-mode and shuffle-mode rows have different
                # schemas: never append misaligned rows under a foreign
                # header — divert to a per-schema sibling file instead
                base, ext = os.path.splitext(path)
                path = f"{base}.{rec.get('mode', 'primitive')}" \
                       f"{ext or '.csv'}"
                print(f"csv schema differs from {csv_path}; "
                      f"writing to {path}", file=sys.stderr)
        new = not os.path.exists(path)
        with open(path, "a", newline="") as f:
            w = csv.DictWriter(f, fieldnames=fields)
            if new:
                w.writeheader()
            w.writerow(rec)
    if sums is not None:
        rec["_sums"] = sums


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--segs", type=int, default=0,
                    help="segments (default: all visible devices)")
    ap.add_argument("--sizes", type=str, default="65536,1048576,16777216",
                    help="per-segment payload bytes, comma-separated "
                         "(primitive mode)")
    ap.add_argument("--backend", default="xla",
                    help="motion transport: xla | ring "
                         "(parallel/transport.py)")
    ap.add_argument("--reps", type=int, default=5)
    ap.add_argument("--format", choices=["packed", "percol", "both"],
                    default=None,
                    help="motion-level shuffle mode: packed (one fused "
                         "all_to_all) vs percol (one collective per "
                         "column); 'both' runs the pair and cross-checks")
    ap.add_argument("--rows", type=int, default=50000,
                    help="rows per segment (shuffle mode)")
    ap.add_argument("--cols", type=int, default=10,
                    help="columns in the shuffled row set")
    ap.add_argument("--skew", type=float, default=0.0,
                    help="fraction of rows sharing one hot key")
    ap.add_argument("--two-level", action="store_true",
                    help="flat vs hierarchical shuffle A/B at a "
                         "simulated multi-host split (CBTPU_FORCE_HOSTS "
                         "process grouping): dcn/ici byte split, "
                         "launches, wall, exact checksum parity")
    ap.add_argument("--hosts", type=int, default=4,
                    help="simulated host count for --two-level "
                         "(must divide the segment count)")
    ap.add_argument("--join-filter", action="store_true",
                    help="PK-FK shuffle with the digest runtime filter "
                         "on vs off: probe rows shipped, wire bytes, "
                         "capacity rung, plus the join-index cache "
                         "repeat microbench")
    ap.add_argument("--dim-rows", type=int, default=2000,
                    help="dimension rows (join-filter mode); fact keys "
                         "span 10x this domain")
    ap.add_argument("--csv", default=None,
                    help="append measurements to this CSV file")
    args = ap.parse_args()

    import jax

    # the terminal's sitecustomize presets the axon TPU relay and imports
    # jax before this script runs, so the JAX_PLATFORMS env var alone is
    # too late — re-assert it through jax.config (tests/conftest.py note)
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    from cloudberry_tpu.parallel.mesh import init_distributed

    init_distributed()
    nseg = args.segs or len(jax.devices())

    if args.two_level:
        # default: a source-concentrated hot key (src_skew puts it on
        # segment 0) — the measured 4-host/8-seg split shows ~3.6x
        # lower DCN bytes (flat pads EVERY source segment's buckets to
        # the hot shard's rung; two-level pads per host pair)
        skew = args.skew if args.skew > 0.0 else 0.7
        bench_two_level(nseg, args.hosts, args.rows, args.cols, skew,
                        args.reps, args.csv)
        return

    if args.join_filter:
        skew = args.skew if args.skew > 0.0 else 0.3
        bench_join_filter(nseg, args.rows, args.dim_rows, skew,
                          args.reps, args.csv)
        return

    if args.format is not None:
        fmts = ["percol", "packed"] if args.format == "both" \
            else [args.format]
        recs = {}
        for fmt in fmts:
            recs[fmt] = bench_shuffle(fmt, nseg, args.rows, args.cols,
                                      args.skew, args.backend, args.reps)
            _emit(recs[fmt], args.csv)
        if len(recs) == 2:
            a, b = recs["percol"]["_sums"], recs["packed"]["_sums"]
            ok = set(a) == set(b) and all(a[k] == b[k] for k in a)
            print(json.dumps({
                "mode": "shuffle-parity",
                "checksums_match": bool(ok),
                "launch_ratio": round(
                    recs["percol"]["collective_launches"]
                    / max(recs["packed"]["collective_launches"], 1), 2),
                "wire_bytes_ratio": round(
                    recs["percol"]["wire_bytes_per_seg"]
                    / max(recs["packed"]["wire_bytes_per_seg"], 1), 3),
            }), flush=True)
        return

    from jax.sharding import PartitionSpec as P

    from cloudberry_tpu.parallel.mesh import SEG_AXIS, segment_mesh
    from cloudberry_tpu.exec.dist_executor import _shard_map

    mesh = segment_mesh(nseg)

    def bench(fn, x, label, nbytes):
        out = jax.block_until_ready(fn(x))
        best = float("inf")
        for _ in range(args.reps):
            t0 = time.time()
            out = jax.block_until_ready(fn(x))
            best = min(best, time.time() - t0)
        rec = {
            "collective": label,
            "payload_bytes_per_seg": nbytes,
            "n_segments": nseg,
            "wall_ms": round(best * 1e3, 3),
            "gbps_per_seg": round(nbytes * 8 / best / 1e9, 3),
        }
        _emit(rec, args.csv)
        return out

    from cloudberry_tpu.parallel.transport import make_transport

    tx = make_transport(args.backend, nseg)

    for size in (int(s) for s in args.sizes.split(",") if s.strip()):
        n = max(size // 4, nseg)           # f32 lanes per segment
        n += (-n) % nseg                   # all_to_all splits evenly
        x = np.arange(nseg * n, dtype=np.float32).reshape(nseg, n)

        def ag(v):
            return tx.all_gather(v[0], SEG_AXIS)

        def a2a(v):
            return tx.all_to_all(v[0].reshape(nseg, n // nseg), SEG_AXIS)

        def ps(v):
            # reduce the FULL payload so the reported bytes really cross
            # the interconnect (a scalar psum would move 4 bytes)
            return tx.psum(v[0], SEG_AXIS)

        for label, fn, spec in (("all_gather", ag, P(SEG_AXIS)),
                                ("all_to_all", a2a, P(SEG_AXIS)),
                                ("psum", ps, P())):
            f = jax.jit(_shard_map(
                lambda v, _fn=fn: _fn(v), mesh,
                (P(SEG_AXIS, None),), spec))
            bench(f, x, label, n * 4)


if __name__ == "__main__":
    main()
