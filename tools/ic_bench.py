"""Standalone interconnect benchmark — the ic_bench / udp2 analog.

The reference ships a kernel-independent interconnect benchmark
(contrib/interconnect/test/ic_bench.c, contrib/udp2's standalone-testable
transport): measure the motion layer WITHOUT the executor on top. Here the
motion layer is XLA collectives over the segment mesh, so this tool times
exactly the three collectives the engine's motions lower to
(exec/dist_executor.py):

- GATHER / BROADCAST  -> all_gather
- HASH redistribute   -> all_to_all
- check reduction     -> psum

Runs on whatever mesh is visible: 8 virtual CPU devices (tests), a real
TPU slice, or a multi-host cluster joined via mesh.init_distributed
(CBTPU_* env). Prints one JSON line per (collective, payload size) with
achieved per-segment bandwidth.

Usage: python -m tools.ic_bench [--segs N] [--sizes bytes,bytes,...]
"""

from __future__ import annotations

import argparse
import json
import time

import numpy as np


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--segs", type=int, default=0,
                    help="segments (default: all visible devices)")
    ap.add_argument("--sizes", type=str, default="65536,1048576,16777216",
                    help="per-segment payload bytes, comma-separated")
    ap.add_argument("--backend", default="xla",
                    help="motion transport: xla | ring "
                         "(parallel/transport.py)")
    ap.add_argument("--reps", type=int, default=5)
    args = ap.parse_args()

    import os

    import jax

    # the terminal's sitecustomize presets the axon TPU relay and imports
    # jax before this script runs, so the JAX_PLATFORMS env var alone is
    # too late — re-assert it through jax.config (tests/conftest.py note)
    if os.environ.get("JAX_PLATFORMS"):
        jax.config.update("jax_platforms", os.environ["JAX_PLATFORMS"])

    from jax.sharding import PartitionSpec as P

    from cloudberry_tpu.parallel.mesh import (SEG_AXIS, init_distributed,
                                              segment_mesh)
    from cloudberry_tpu.exec.dist_executor import _shard_map

    init_distributed()
    nseg = args.segs or len(jax.devices())
    mesh = segment_mesh(nseg)

    def bench(fn, x, label, nbytes):
        out = jax.block_until_ready(fn(x))
        best = float("inf")
        for _ in range(args.reps):
            t0 = time.time()
            out = jax.block_until_ready(fn(x))
            best = min(best, time.time() - t0)
        print(json.dumps({
            "collective": label,
            "payload_bytes_per_seg": nbytes,
            "n_segments": nseg,
            "wall_ms": round(best * 1e3, 3),
            "gbps_per_seg": round(nbytes * 8 / best / 1e9, 3),
        }), flush=True)
        return out

    from cloudberry_tpu.parallel.transport import make_transport

    tx = make_transport(args.backend, nseg)

    for size in (int(s) for s in args.sizes.split(",") if s.strip()):
        n = max(size // 4, nseg)           # f32 lanes per segment
        n += (-n) % nseg                   # all_to_all splits evenly
        x = np.arange(nseg * n, dtype=np.float32).reshape(nseg, n)

        def ag(v):
            return tx.all_gather(v[0], SEG_AXIS)

        def a2a(v):
            return tx.all_to_all(v[0].reshape(nseg, n // nseg), SEG_AXIS)

        def ps(v):
            # reduce the FULL payload so the reported bytes really cross
            # the interconnect (a scalar psum would move 4 bytes)
            return tx.psum(v[0], SEG_AXIS)

        for label, fn, spec in (("all_gather", ag, P(SEG_AXIS)),
                                ("all_to_all", a2a, P(SEG_AXIS)),
                                ("psum", ps, P())):
            f = jax.jit(_shard_map(
                lambda v, _fn=fn: _fn(v), mesh,
                (P(SEG_AXIS, None),), spec))
            bench(f, x, label, n * 4)


if __name__ == "__main__":
    main()
