"""Golden plan-shape snapshots — the ORCA minidump-replay analog.

`python -m tools.golden_plans` regenerates tests/golden/*.plan for every
TPC-H query in single-segment and 8-segment modes; the committed files are
the expected plans, and tests/test_golden_plans.py fails on any regression
(capacity changes, motion placement, join order, share nodes...). Like the
reference's 1,246 .mdp fixtures, this pins optimizer behavior with no
cluster and no oracle run.
"""

from __future__ import annotations

import os

GOLDEN_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tests", "golden")

SF = 0.01
SEED = 7


def make_session(nseg: int):
    import cloudberry_tpu as cb
    from cloudberry_tpu.config import Config
    from tools.tpchgen import load_tpch

    s = cb.Session(Config(n_segments=nseg)) if nseg > 1 else cb.Session()
    load_tpch(s, sf=SF, seed=SEED)
    return s


def plan_text(session, sql: str) -> str:
    return session.explain(sql).rstrip() + "\n"


def snapshot_name(qname: str, nseg: int) -> str:
    return f"{qname}_seg{nseg}.plan"


def regenerate() -> list[str]:
    from tools.tpch_queries import QUERIES

    os.makedirs(GOLDEN_DIR, exist_ok=True)
    written = []
    for nseg in (1, 8):
        s = make_session(nseg)
        for qname in sorted(QUERIES):
            text = plan_text(s, QUERIES[qname])
            path = os.path.join(GOLDEN_DIR, snapshot_name(qname, nseg))
            with open(path, "w") as fh:
                fh.write(text)
            written.append(path)
    return written


if __name__ == "__main__":
    import jax

    jax.config.update("jax_platforms", "cpu")
    for p in regenerate():
        print(p)
