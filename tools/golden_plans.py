"""Golden plan-shape snapshots — the ORCA minidump-replay analog.

`python -m tools.golden_plans` regenerates tests/golden/*.plan for every
TPC-H query AND every supported TPC-DS query in single-segment and
8-segment modes; the committed files are the expected plans, and
tests/test_golden_plans.py fails on any regression (capacity changes,
motion placement, join order, share nodes, the ``dist:`` derived-
distribution annotations...). Like the reference's 1,246 .mdp fixtures,
this pins optimizer behavior with no cluster and no oracle run.

Every plan in the corpus is additionally run through the planck
verifier (plan/verify.py) — sessions here carry
``config.debug.verify_plans``, so regeneration REFUSES to write a
golden file for a plan that fails its derived-vs-required property
check, and the test suite re-verifies on every run: a corrupted golden
plan is a test failure with a node-path diagnostic, not a silent
replan.
"""

from __future__ import annotations

import os

GOLDEN_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tests", "golden")

SF = 0.01
SEED = 7
DS_SCALE = 0.5
DS_SEED = 11


def _config(nseg: int, verify: bool = True):
    from cloudberry_tpu.config import Config

    # the golden corpus verifies by default: every planned statement
    # runs the planck gate (plan/verify.py) before its text is
    # snapshotted. verify=False serves verify_corpus, which calls the
    # verifier itself to COLLECT findings instead of raising.
    return Config(n_segments=nseg).with_overrides(
        **{"debug.verify_plans": verify})


def make_session(nseg: int, verify: bool = True):
    import cloudberry_tpu as cb
    from tools.tpchgen import load_tpch

    s = cb.Session(_config(nseg, verify))
    load_tpch(s, sf=SF, seed=SEED)
    return s


def make_ds_session(nseg: int, verify: bool = True):
    import cloudberry_tpu as cb
    from tools.tpcdsgen import load_tpcds

    s = cb.Session(_config(nseg, verify))
    load_tpcds(s, scale=DS_SCALE, seed=DS_SEED)
    return s


def plan_text(session, sql: str) -> str:
    return session.explain(sql).rstrip() + "\n"


def snapshot_name(qname: str, nseg: int, suite: str = "tpch") -> str:
    prefix = "ds_" if suite == "tpcds" else ""
    return f"{prefix}{qname}_seg{nseg}.plan"


def corpus() -> list[tuple[str, object, dict]]:
    """(suite, session factory, queries) per benchmark corpus — THE
    one place that knows which loader serves which suite."""
    from tools.tpcds_queries import DS_QUERIES
    from tools.tpch_queries import QUERIES

    return [("tpch", make_session, QUERIES),
            ("tpcds", make_ds_session, DS_QUERIES)]


def verify_corpus(nsegs=(1, 8)) -> dict:
    """Plan + verify the WHOLE golden corpus (no files touched): the
    tools/lint_gate.py --plans and bench.py ``planverify`` currency.
    Returns {"plans", "nodes", "rules_hit", "findings", "wall_s"}."""
    import time

    from cloudberry_tpu.plan.planner import plan_statement
    from cloudberry_tpu.plan.verify import Verifier
    from cloudberry_tpu.sql.parser import parse_sql

    t0 = time.perf_counter()
    plans = nodes = 0
    rules: set[str] = set()
    findings: list[dict] = []
    for nseg in nsegs:
        for suite, factory, queries in corpus():
            # ungated session: this sweep runs the Verifier itself to
            # COLLECT findings (one bad plan reports, never aborts)
            s = factory(nseg, verify=False)
            for qname in sorted(queries):
                r = plan_statement(parse_sql(queries[qname]), s, {})
                v = Verifier(s, r.plan)
                for f in v.verify(r.plan):
                    findings.append({"suite": suite, "query": qname,
                                     "nseg": nseg, **f.as_dict()})
                plans += 1
                nodes += v.nodes_checked
                rules |= v.rules_hit
    return {"plans": plans, "nodes": nodes,
            "rules_hit": sorted(rules), "findings": findings,
            "wall_s": time.perf_counter() - t0}


def regenerate() -> list[str]:
    os.makedirs(GOLDEN_DIR, exist_ok=True)
    written = []
    for nseg in (1, 8):
        for suite, factory, queries in corpus():
            s = factory(nseg)
            for qname in sorted(queries):
                text = plan_text(s, queries[qname])
                path = os.path.join(
                    GOLDEN_DIR, snapshot_name(qname, nseg, suite))
                with open(path, "w") as fh:
                    fh.write(text)
                written.append(path)
    return written


if __name__ == "__main__":
    import jax

    jax.config.update("jax_platforms", "cpu")
    for p in regenerate():
        print(p)
