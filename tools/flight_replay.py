"""Offline flight-bundle replay — re-execute a captured slow statement
and assert bit-identical results.

The flight recorder (cloudberry_tpu/obs/flightrec.py) captures a slow
or erroring statement's debug bundle, including a sha256 digest over
the DECODED result columns. This tool closes the forensics loop: given
a bundle (a file saved from ``meta "flight"``, or the export list
itself), it opens a fresh session against the bundle's durable store,
re-executes the sql, and compares digests — the replay contract from
docs/DESIGN.md "Capacity & forensics plane":

    same store version + same statement text + same config shape
    ⇒ the same bytes, or the replay FAILS loudly.

A digest mismatch means the store moved underneath (a later commit),
the engine regressed, or the bundle is from a different cluster — all
three are exactly what a forensics session needs to know first.

Usage:
    python tools/flight_replay.py bundle.json [--index N] [--root DIR]
        [--segments N]

``bundle.json`` may hold one bundle, a list, or a ``meta "flight"``
response ({"flights": [...]}); --index picks from a list (default 0,
the newest). --root / --segments override the bundle's recorded store
root and mesh width (e.g. the store was copied for offline analysis).
Exit 0 on a bit-identical replay, 1 on mismatch, 2 on an unreplayable
bundle (no store root, no result digest, or non-JSON bind params).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def pick_bundle(doc, index: int = 0) -> dict:
    """One bundle out of whatever shape the file holds."""
    if isinstance(doc, dict) and "flights" in doc:
        doc = doc["flights"]
    if isinstance(doc, dict) and "meta" in doc \
            and isinstance(doc["meta"], dict):
        doc = doc["meta"].get("flights", doc)
    if isinstance(doc, list):
        if not doc:
            raise ValueError("empty flight list")
        return doc[index]
    if isinstance(doc, dict):
        return doc
    raise ValueError(f"unrecognized bundle document: {type(doc).__name__}")


def replay(bundle: dict, session=None, root: str | None = None,
           n_segments: int | None = None) -> dict:
    """Re-execute one bundle; returns the verdict record:
    ``{"ok": bool, "expected": digest, "got": digest, ...}``.
    ``session`` overrides session construction (tests pass the live
    session to assert replay-on-the-same-engine first)."""
    from cloudberry_tpu.obs import flightrec

    expected = bundle.get("result")
    if expected is None:
        return {"ok": False, "unreplayable":
                "bundle has no result digest (errored or DML statement)"}
    params = bundle.get("params") or {}
    if session is None:
        store_root = root or bundle.get("storage_root")
        if not store_root:
            return {"ok": False, "unreplayable":
                    "bundle has no storage root (in-memory session) — "
                    "pass --root to point at a copied store"}
        import cloudberry_tpu as cb
        from cloudberry_tpu.config import Config

        nseg = n_segments if n_segments is not None \
            else int(bundle.get("n_segments", 1))
        session = cb.Session(Config().with_overrides(**{
            "storage.root": store_root, "n_segments": nseg}))
    out = session.sql(bundle["sql"], **params)
    got = flightrec.result_digest(out)
    ok = bool(got is not None
              and got["sha256"] == expected.get("sha256")
              and got["rows"] == expected.get("rows"))
    return {"ok": ok, "expected": expected, "got": got,
            "sql": bundle["sql"][:200]}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("bundle", help="bundle JSON file (one bundle, a "
                                   "list, or a meta 'flight' response)")
    ap.add_argument("--index", type=int, default=0,
                    help="which bundle when the file holds a list "
                         "(0 = newest)")
    ap.add_argument("--root", default=None,
                    help="override the bundle's storage root")
    ap.add_argument("--segments", type=int, default=None,
                    help="override the bundle's segment count")
    args = ap.parse_args(argv)

    with open(args.bundle) as fh:
        bundle = pick_bundle(json.load(fh), args.index)
    verdict = replay(bundle, root=args.root, n_segments=args.segments)
    if verdict.get("unreplayable"):
        print(f"UNREPLAYABLE: {verdict['unreplayable']}", file=sys.stderr)
        return 2
    if verdict["ok"]:
        print(f"OK: bit-identical replay "
              f"({verdict['expected']['rows']} rows, "
              f"sha256 {verdict['expected']['sha256'][:16]}…)")
        return 0
    print("MISMATCH:", file=sys.stderr)
    print(f"  expected {verdict['expected']}", file=sys.stderr)
    print(f"  got      {verdict['got']}", file=sys.stderr)
    return 1


if __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    sys.exit(main())
