"""pandas reference implementations of TPC-H queries — the expected-output
oracle for correctness tests (the pg_regress expected-file analog, computed
rather than stored so it tracks the generator)."""

from __future__ import annotations

import numpy as np
import pandas as pd


def d(s: str) -> np.datetime64:
    return np.datetime64(s)


def q1(t):
    li = t["lineitem"]
    m = li[li.l_shipdate <= d("1998-09-02")].copy()
    m["disc_price"] = m.l_extendedprice * (1 - m.l_discount)
    m["charge"] = m.disc_price * (1 + m.l_tax)
    g = m.groupby(["l_returnflag", "l_linestatus"], as_index=False).agg(
        sum_qty=("l_quantity", "sum"),
        sum_base_price=("l_extendedprice", "sum"),
        sum_disc_price=("disc_price", "sum"),
        sum_charge=("charge", "sum"),
        avg_qty=("l_quantity", "mean"),
        avg_price=("l_extendedprice", "mean"),
        avg_disc=("l_discount", "mean"),
        count_order=("l_quantity", "size"),
    )
    return g.sort_values(["l_returnflag", "l_linestatus"]).reset_index(drop=True)


def q3(t):
    li, od, cu = t["lineitem"], t["orders"], t["customer"]
    j = od.merge(cu[cu.c_mktsegment == "BUILDING"],
                 left_on="o_custkey", right_on="c_custkey")
    j = li.merge(j, left_on="l_orderkey", right_on="o_orderkey")
    j = j[(j.o_orderdate < d("1995-03-15")) & (j.l_shipdate > d("1995-03-15"))]
    j["revenue"] = j.l_extendedprice * (1 - j.l_discount)
    g = j.groupby(["l_orderkey", "o_orderdate", "o_shippriority"],
                  as_index=False)["revenue"].sum()
    g = g.sort_values(["revenue", "o_orderdate"], ascending=[False, True],
                      kind="stable").head(10)
    return g[["l_orderkey", "revenue", "o_orderdate", "o_shippriority"]] \
        .reset_index(drop=True)


def q5(t):
    li, od, cu = t["lineitem"], t["orders"], t["customer"]
    su, na, re = t["supplier"], t["nation"], t["region"]
    j = li.merge(od, left_on="l_orderkey", right_on="o_orderkey")
    j = j.merge(cu, left_on="o_custkey", right_on="c_custkey")
    j = j.merge(su, left_on="l_suppkey", right_on="s_suppkey")
    j = j[j.c_nationkey == j.s_nationkey]
    j = j.merge(na, left_on="s_nationkey", right_on="n_nationkey")
    j = j.merge(re, left_on="n_regionkey", right_on="r_regionkey")
    j = j[(j.r_name == "ASIA") & (j.o_orderdate >= d("1994-01-01"))
          & (j.o_orderdate < d("1995-01-01"))]
    j["revenue"] = j.l_extendedprice * (1 - j.l_discount)
    g = j.groupby("n_name", as_index=False)["revenue"].sum()
    return g.sort_values("revenue", ascending=False).reset_index(drop=True)


def q6(t):
    li = t["lineitem"]
    m = (li.l_shipdate >= d("1994-01-01")) & (li.l_shipdate < d("1995-01-01")) \
        & (li.l_discount >= 0.05) & (li.l_discount <= 0.07) & (li.l_quantity < 24)
    return pd.DataFrame({
        "revenue": [(li[m].l_extendedprice * li[m].l_discount).sum()]})


def q10(t):
    li, od, cu, na = t["lineitem"], t["orders"], t["customer"], t["nation"]
    j = li[li.l_returnflag == "R"].merge(
        od[(od.o_orderdate >= d("1993-10-01"))
           & (od.o_orderdate < d("1994-01-01"))],
        left_on="l_orderkey", right_on="o_orderkey")
    j = j.merge(cu, left_on="o_custkey", right_on="c_custkey")
    j = j.merge(na, left_on="c_nationkey", right_on="n_nationkey")
    j["revenue"] = j.l_extendedprice * (1 - j.l_discount)
    g = j.groupby(["c_custkey", "c_name", "c_acctbal", "c_phone", "n_name",
                   "c_address", "c_comment"], as_index=False)["revenue"].sum()
    g = g.sort_values("revenue", ascending=False, kind="stable").head(20)
    return g[["c_custkey", "c_name", "revenue", "c_acctbal", "n_name",
              "c_address", "c_phone", "c_comment"]].reset_index(drop=True)


def q12(t):
    li, od = t["lineitem"], t["orders"]
    m = li[li.l_shipmode.isin(["MAIL", "SHIP"])
           & (li.l_commitdate < li.l_receiptdate)
           & (li.l_shipdate < li.l_commitdate)
           & (li.l_receiptdate >= d("1994-01-01"))
           & (li.l_receiptdate < d("1995-01-01"))]
    j = m.merge(od, left_on="l_orderkey", right_on="o_orderkey")
    hi = j.o_orderpriority.isin(["1-URGENT", "2-HIGH"])
    g = j.assign(high=hi.astype(int), low=(~hi).astype(int)).groupby(
        "l_shipmode", as_index=False).agg(
        high_line_count=("high", "sum"), low_line_count=("low", "sum"))
    return g.sort_values("l_shipmode").reset_index(drop=True)


def q14(t):
    li, pa = t["lineitem"], t["part"]
    j = li[(li.l_shipdate >= d("1995-09-01"))
           & (li.l_shipdate < d("1995-10-01"))].merge(
        pa, left_on="l_partkey", right_on="p_partkey")
    rev = j.l_extendedprice * (1 - j.l_discount)
    promo = rev.where(j.p_type.str.startswith("PROMO"), 0.0)
    return pd.DataFrame({
        "promo_revenue": [100.0 * promo.sum() / rev.sum()]})


def q19(t):
    li, pa = t["lineitem"], t["part"]
    j = li.merge(pa, left_on="l_partkey", right_on="p_partkey")
    base = j.l_shipmode.isin(["AIR", "AIR REG"]) \
        & (j.l_shipinstruct == "DELIVER IN PERSON")

    def branch(brand, containers, qlo, qhi, slo, shi):
        return ((j.p_brand == brand) & j.p_container.isin(containers)
                & (j.l_quantity >= qlo) & (j.l_quantity <= qhi)
                & (j.p_size >= slo) & (j.p_size <= shi))

    m = base & (
        branch("Brand#12", ["SM CASE", "SM BOX", "SM PACK", "SM PKG"], 1, 11, 1, 5)
        | branch("Brand#23", ["MED BAG", "MED BOX", "MED PKG", "MED PACK"], 10, 20, 1, 10)
        | branch("Brand#34", ["LG CASE", "LG BOX", "LG PACK", "LG PKG"], 20, 30, 1, 15))
    return pd.DataFrame({
        "revenue": [(j[m].l_extendedprice * (1 - j[m].l_discount)).sum()]})


ORACLES = {"q1": q1, "q3": q3, "q5": q5, "q6": q6, "q10": q10, "q12": q12,
           "q14": q14, "q19": q19}


def q2(t):
    pa, su, ps, na, re = (t["part"], t["supplier"], t["partsupp"],
                          t["nation"], t["region"])
    eu = na.merge(re, left_on="n_regionkey", right_on="r_regionkey")
    eu = eu[eu.r_name == "EUROPE"]
    s_eu = su.merge(eu, left_on="s_nationkey", right_on="n_nationkey")
    j = ps.merge(s_eu, left_on="ps_suppkey", right_on="s_suppkey")
    mincost = j.groupby("ps_partkey")["ps_supplycost"].min().rename("mc")
    p = pa[(pa.p_size == 15) & pa.p_type.str.endswith("BRASS")]
    j2 = j.merge(p, left_on="ps_partkey", right_on="p_partkey")
    j2 = j2.merge(mincost, left_on="ps_partkey", right_index=True)
    j2 = j2[j2.ps_supplycost == j2.mc]
    j2 = j2.sort_values(["s_acctbal", "n_name", "s_name", "p_partkey"],
                        ascending=[False, True, True, True],
                        kind="stable").head(100)
    return j2[["s_acctbal", "s_name", "n_name", "p_partkey", "p_mfgr",
               "s_address", "s_phone", "s_comment"]].reset_index(drop=True)


def q4(t):
    od, li = t["orders"], t["lineitem"]
    late = li[li.l_commitdate < li.l_receiptdate].l_orderkey.unique()
    m = od[(od.o_orderdate >= d("1993-07-01"))
           & (od.o_orderdate < d("1993-10-01"))
           & od.o_orderkey.isin(late)]
    g = m.groupby("o_orderpriority", as_index=False).size()
    g.columns = ["o_orderpriority", "order_count"]
    return g.sort_values("o_orderpriority").reset_index(drop=True)


def q7(t):
    li, od, cu, su, na = (t["lineitem"], t["orders"], t["customer"],
                          t["supplier"], t["nation"])
    j = li.merge(od, left_on="l_orderkey", right_on="o_orderkey")
    j = j.merge(cu, left_on="o_custkey", right_on="c_custkey")
    j = j.merge(su, left_on="l_suppkey", right_on="s_suppkey")
    j = j.merge(na.add_prefix("s1_"), left_on="s_nationkey",
                right_on="s1_n_nationkey")
    j = j.merge(na.add_prefix("c2_"), left_on="c_nationkey",
                right_on="c2_n_nationkey")
    j = j[(((j.s1_n_name == "FRANCE") & (j.c2_n_name == "GERMANY"))
           | ((j.s1_n_name == "GERMANY") & (j.c2_n_name == "FRANCE")))
          & (j.l_shipdate >= d("1995-01-01"))
          & (j.l_shipdate <= d("1996-12-31"))]
    j["l_year"] = j.l_shipdate.dt.year
    j["volume"] = j.l_extendedprice * (1 - j.l_discount)
    g = j.groupby(["s1_n_name", "c2_n_name", "l_year"],
                  as_index=False)["volume"].sum()
    g.columns = ["supp_nation", "cust_nation", "l_year", "revenue"]
    return g.sort_values(["supp_nation", "cust_nation", "l_year"]) \
        .reset_index(drop=True)


def q8(t):
    li, od, cu, su, pa, na, re = (t["lineitem"], t["orders"], t["customer"],
                                  t["supplier"], t["part"], t["nation"],
                                  t["region"])
    j = li.merge(pa[pa.p_type == "ECONOMY ANODIZED STEEL"],
                 left_on="l_partkey", right_on="p_partkey")
    j = j.merge(od, left_on="l_orderkey", right_on="o_orderkey")
    j = j[(j.o_orderdate >= d("1995-01-01")) & (j.o_orderdate <= d("1996-12-31"))]
    j = j.merge(cu, left_on="o_custkey", right_on="c_custkey")
    j = j.merge(na.add_prefix("c1_"), left_on="c_nationkey",
                right_on="c1_n_nationkey")
    j = j.merge(re, left_on="c1_n_regionkey", right_on="r_regionkey")
    j = j[j.r_name == "AMERICA"]
    j = j.merge(su, left_on="l_suppkey", right_on="s_suppkey")
    j = j.merge(na.add_prefix("s2_"), left_on="s_nationkey",
                right_on="s2_n_nationkey")
    j["o_year"] = j.o_orderdate.dt.year
    j["volume"] = j.l_extendedprice * (1 - j.l_discount)
    j["bra"] = j.volume.where(j.s2_n_name == "BRAZIL", 0.0)
    g = j.groupby("o_year", as_index=False).agg(b=("bra", "sum"),
                                                v=("volume", "sum"))
    g["mkt_share"] = g.b / g.v
    return g[["o_year", "mkt_share"]].sort_values("o_year") \
        .reset_index(drop=True)


def q9(t):
    li, od, su, pa, ps, na = (t["lineitem"], t["orders"], t["supplier"],
                              t["part"], t["partsupp"], t["nation"])
    j = li.merge(pa[pa.p_name.str.contains("green")],
                 left_on="l_partkey", right_on="p_partkey")
    j = j.merge(ps, left_on=["l_partkey", "l_suppkey"],
                right_on=["ps_partkey", "ps_suppkey"])
    j = j.merge(su, left_on="l_suppkey", right_on="s_suppkey")
    j = j.merge(od, left_on="l_orderkey", right_on="o_orderkey")
    j = j.merge(na, left_on="s_nationkey", right_on="n_nationkey")
    j["o_year"] = j.o_orderdate.dt.year
    j["amount"] = (j.l_extendedprice * (1 - j.l_discount)
                   - j.ps_supplycost * j.l_quantity)
    g = j.groupby(["n_name", "o_year"], as_index=False)["amount"].sum()
    g.columns = ["nation", "o_year", "sum_profit"]
    return g.sort_values(["nation", "o_year"], ascending=[True, False]) \
        .reset_index(drop=True)


def q11(t):
    ps, su, na = t["partsupp"], t["supplier"], t["nation"]
    j = ps.merge(su, left_on="ps_suppkey", right_on="s_suppkey")
    j = j.merge(na[na.n_name == "GERMANY"], left_on="s_nationkey",
                right_on="n_nationkey")
    j["value"] = j.ps_supplycost * j.ps_availqty
    total = j.value.sum() * 0.0001
    g = j.groupby("ps_partkey", as_index=False)["value"].sum()
    g = g[g.value > total]
    return g.sort_values("value", ascending=False).reset_index(drop=True)


def q13(t):
    cu, od = t["customer"], t["orders"]
    o = od[~od.o_comment.str.contains("special.*requests", regex=True)]
    cnt = o.groupby("o_custkey").size()
    c_count = cu.c_custkey.map(cnt).fillna(0).astype(int)
    g = c_count.value_counts().rename_axis("c_count") \
        .reset_index(name="custdist")
    return g.sort_values(["custdist", "c_count"], ascending=[False, False]) \
        .reset_index(drop=True)


def q15(t):
    li, su = t["lineitem"], t["supplier"]
    m = li[(li.l_shipdate >= d("1996-01-01")) & (li.l_shipdate < d("1996-04-01"))]
    rev = m.assign(r=m.l_extendedprice * (1 - m.l_discount)) \
        .groupby("l_suppkey", as_index=False)["r"].sum()
    mx = rev.r.max()
    j = su.merge(rev[rev.r == mx], left_on="s_suppkey", right_on="l_suppkey")
    j = j.sort_values("s_suppkey")
    out = j[["s_suppkey", "s_name", "s_address", "s_phone", "r"]].copy()
    out.columns = ["s_suppkey", "s_name", "s_address", "s_phone",
                   "total_revenue"]
    return out.reset_index(drop=True)


def q16(t):
    ps, pa, su = t["partsupp"], t["part"], t["supplier"]
    bad = su[su.s_comment.str.contains("Customer.*Complaints", regex=True)] \
        .s_suppkey
    p = pa[(pa.p_brand != "Brand#45")
           & ~pa.p_type.str.startswith("MEDIUM POLISHED")
           & pa.p_size.isin([49, 14, 23, 45, 19, 3, 36, 9])]
    j = ps.merge(p, left_on="ps_partkey", right_on="p_partkey")
    j = j[~j.ps_suppkey.isin(bad)]
    g = j.groupby(["p_brand", "p_type", "p_size"])["ps_suppkey"] \
        .nunique().reset_index(name="supplier_cnt")
    return g.sort_values(["supplier_cnt", "p_brand", "p_type", "p_size"],
                         ascending=[False, True, True, True]) \
        .reset_index(drop=True)


def q17(t):
    li, pa = t["lineitem"], t["part"]
    p = pa[(pa.p_brand == "Brand#23") & (pa.p_container == "MED BOX")]
    avg_q = li.groupby("l_partkey")["l_quantity"].mean() * 0.2
    j = li.merge(p, left_on="l_partkey", right_on="p_partkey")
    j = j[j.l_quantity < j.l_partkey.map(avg_q)]
    # SQL: sum() over zero rows is NULL, not 0 (pandas' .sum() default)
    total = j.l_extendedprice.sum() / 7.0 if len(j) else float("nan")
    return pd.DataFrame({"avg_yearly": [total]})


def q18(t):
    cu, od, li = t["customer"], t["orders"], t["lineitem"]
    big = li.groupby("l_orderkey")["l_quantity"].sum()
    big = big[big > 300].index
    j = od[od.o_orderkey.isin(big)].merge(cu, left_on="o_custkey",
                                          right_on="c_custkey")
    j = li.merge(j, left_on="l_orderkey", right_on="o_orderkey")
    g = j.groupby(["c_name", "c_custkey", "o_orderkey", "o_orderdate",
                   "o_totalprice"], as_index=False)["l_quantity"].sum()
    g.columns = ["c_name", "c_custkey", "o_orderkey", "o_orderdate",
                 "o_totalprice", "total_qty"]
    g = g.sort_values(["o_totalprice", "o_orderdate"],
                      ascending=[False, True], kind="stable").head(100)
    return g.reset_index(drop=True)


def q20(t):
    su, na, ps, pa, li = (t["supplier"], t["nation"], t["partsupp"],
                          t["part"], t["lineitem"])
    forest = pa[pa.p_name.str.startswith("forest")].p_partkey
    m = li[(li.l_shipdate >= d("1994-01-01")) & (li.l_shipdate < d("1995-01-01"))]
    half = m.groupby(["l_partkey", "l_suppkey"])["l_quantity"].sum() * 0.5
    j = ps[ps.ps_partkey.isin(forest)].copy()
    key = list(zip(j.ps_partkey, j.ps_suppkey))
    j["thresh"] = [half.get(k, np.nan) for k in key]
    j = j[j.ps_availqty > j.thresh]  # NaN comparison false = SQL NULL false
    sk = j.ps_suppkey.unique()
    out = su[su.s_suppkey.isin(sk)].merge(
        na[na.n_name == "CANADA"], left_on="s_nationkey",
        right_on="n_nationkey")
    return out.sort_values("s_name")[["s_name", "s_address"]] \
        .reset_index(drop=True)


def q21(t):
    su, li, od, na = t["supplier"], t["lineitem"], t["orders"], t["nation"]
    l1 = li[li.l_receiptdate > li.l_commitdate]
    nsupp = li.groupby("l_orderkey")["l_suppkey"].nunique()
    late_nsupp = l1.groupby("l_orderkey")["l_suppkey"].nunique()
    j = l1.merge(od[od.o_orderstatus == "F"], left_on="l_orderkey",
                 right_on="o_orderkey")
    j = j.merge(su, left_on="l_suppkey", right_on="s_suppkey")
    j = j.merge(na[na.n_name == "SAUDI ARABIA"], left_on="s_nationkey",
                right_on="n_nationkey")
    # exists: order has another supplier; not exists: no OTHER supplier late
    j = j[(j.l_orderkey.map(nsupp) > 1)]
    other_late = [
        (late_nsupp.get(ok, 0) - 1 if is_late else late_nsupp.get(ok, 0)) > 0
        for ok, is_late in zip(j.l_orderkey, [True] * len(j))]
    j = j[~np.asarray(other_late)]
    g = j.groupby("s_name", as_index=False).size()
    g.columns = ["s_name", "numwait"]
    g = g.sort_values(["numwait", "s_name"], ascending=[False, True],
                      kind="stable").head(100)
    return g.reset_index(drop=True)


def q22(t):
    cu, od = t["customer"], t["orders"]
    codes = ["13", "31", "23", "29", "30", "18", "17"]
    cc = cu.c_phone.str[:2]
    pool = cu[cc.isin(codes)]
    avg_bal = pool[pool.c_acctbal > 0.0].c_acctbal.mean()
    m = pool[(pool.c_acctbal > avg_bal)
             & ~pool.c_custkey.isin(od.o_custkey)]
    g = m.assign(cntrycode=m.c_phone.str[:2]).groupby(
        "cntrycode", as_index=False).agg(numcust=("c_acctbal", "size"),
                                         totacctbal=("c_acctbal", "sum"))
    return g.sort_values("cntrycode").reset_index(drop=True)


ORACLES.update({"q2": q2, "q4": q4, "q7": q7, "q8": q8, "q9": q9,
                "q11": q11, "q13": q13, "q15": q15, "q16": q16, "q17": q17,
                "q18": q18, "q20": q20, "q21": q21, "q22": q22})
