"""pandas reference implementations of TPC-H queries — the expected-output
oracle for correctness tests (the pg_regress expected-file analog, computed
rather than stored so it tracks the generator)."""

from __future__ import annotations

import numpy as np
import pandas as pd


def d(s: str) -> np.datetime64:
    return np.datetime64(s)


def q1(t):
    li = t["lineitem"]
    m = li[li.l_shipdate <= d("1998-09-02")].copy()
    m["disc_price"] = m.l_extendedprice * (1 - m.l_discount)
    m["charge"] = m.disc_price * (1 + m.l_tax)
    g = m.groupby(["l_returnflag", "l_linestatus"], as_index=False).agg(
        sum_qty=("l_quantity", "sum"),
        sum_base_price=("l_extendedprice", "sum"),
        sum_disc_price=("disc_price", "sum"),
        sum_charge=("charge", "sum"),
        avg_qty=("l_quantity", "mean"),
        avg_price=("l_extendedprice", "mean"),
        avg_disc=("l_discount", "mean"),
        count_order=("l_quantity", "size"),
    )
    return g.sort_values(["l_returnflag", "l_linestatus"]).reset_index(drop=True)


def q3(t):
    li, od, cu = t["lineitem"], t["orders"], t["customer"]
    j = od.merge(cu[cu.c_mktsegment == "BUILDING"],
                 left_on="o_custkey", right_on="c_custkey")
    j = li.merge(j, left_on="l_orderkey", right_on="o_orderkey")
    j = j[(j.o_orderdate < d("1995-03-15")) & (j.l_shipdate > d("1995-03-15"))]
    j["revenue"] = j.l_extendedprice * (1 - j.l_discount)
    g = j.groupby(["l_orderkey", "o_orderdate", "o_shippriority"],
                  as_index=False)["revenue"].sum()
    g = g.sort_values(["revenue", "o_orderdate"], ascending=[False, True],
                      kind="stable").head(10)
    return g[["l_orderkey", "revenue", "o_orderdate", "o_shippriority"]] \
        .reset_index(drop=True)


def q5(t):
    li, od, cu = t["lineitem"], t["orders"], t["customer"]
    su, na, re = t["supplier"], t["nation"], t["region"]
    j = li.merge(od, left_on="l_orderkey", right_on="o_orderkey")
    j = j.merge(cu, left_on="o_custkey", right_on="c_custkey")
    j = j.merge(su, left_on="l_suppkey", right_on="s_suppkey")
    j = j[j.c_nationkey == j.s_nationkey]
    j = j.merge(na, left_on="s_nationkey", right_on="n_nationkey")
    j = j.merge(re, left_on="n_regionkey", right_on="r_regionkey")
    j = j[(j.r_name == "ASIA") & (j.o_orderdate >= d("1994-01-01"))
          & (j.o_orderdate < d("1995-01-01"))]
    j["revenue"] = j.l_extendedprice * (1 - j.l_discount)
    g = j.groupby("n_name", as_index=False)["revenue"].sum()
    return g.sort_values("revenue", ascending=False).reset_index(drop=True)


def q6(t):
    li = t["lineitem"]
    m = (li.l_shipdate >= d("1994-01-01")) & (li.l_shipdate < d("1995-01-01")) \
        & (li.l_discount >= 0.05) & (li.l_discount <= 0.07) & (li.l_quantity < 24)
    return pd.DataFrame({
        "revenue": [(li[m].l_extendedprice * li[m].l_discount).sum()]})


def q10(t):
    li, od, cu, na = t["lineitem"], t["orders"], t["customer"], t["nation"]
    j = li[li.l_returnflag == "R"].merge(
        od[(od.o_orderdate >= d("1993-10-01"))
           & (od.o_orderdate < d("1994-01-01"))],
        left_on="l_orderkey", right_on="o_orderkey")
    j = j.merge(cu, left_on="o_custkey", right_on="c_custkey")
    j = j.merge(na, left_on="c_nationkey", right_on="n_nationkey")
    j["revenue"] = j.l_extendedprice * (1 - j.l_discount)
    g = j.groupby(["c_custkey", "c_name", "c_acctbal", "c_phone", "n_name",
                   "c_address", "c_comment"], as_index=False)["revenue"].sum()
    g = g.sort_values("revenue", ascending=False, kind="stable").head(20)
    return g[["c_custkey", "c_name", "revenue", "c_acctbal", "n_name",
              "c_address", "c_phone", "c_comment"]].reset_index(drop=True)


def q12(t):
    li, od = t["lineitem"], t["orders"]
    m = li[li.l_shipmode.isin(["MAIL", "SHIP"])
           & (li.l_commitdate < li.l_receiptdate)
           & (li.l_shipdate < li.l_commitdate)
           & (li.l_receiptdate >= d("1994-01-01"))
           & (li.l_receiptdate < d("1995-01-01"))]
    j = m.merge(od, left_on="l_orderkey", right_on="o_orderkey")
    hi = j.o_orderpriority.isin(["1-URGENT", "2-HIGH"])
    g = j.assign(high=hi.astype(int), low=(~hi).astype(int)).groupby(
        "l_shipmode", as_index=False).agg(
        high_line_count=("high", "sum"), low_line_count=("low", "sum"))
    return g.sort_values("l_shipmode").reset_index(drop=True)


def q14(t):
    li, pa = t["lineitem"], t["part"]
    j = li[(li.l_shipdate >= d("1995-09-01"))
           & (li.l_shipdate < d("1995-10-01"))].merge(
        pa, left_on="l_partkey", right_on="p_partkey")
    rev = j.l_extendedprice * (1 - j.l_discount)
    promo = rev.where(j.p_type.str.startswith("PROMO"), 0.0)
    return pd.DataFrame({
        "promo_revenue": [100.0 * promo.sum() / rev.sum()]})


def q19(t):
    li, pa = t["lineitem"], t["part"]
    j = li.merge(pa, left_on="l_partkey", right_on="p_partkey")
    base = j.l_shipmode.isin(["AIR", "AIR REG"]) \
        & (j.l_shipinstruct == "DELIVER IN PERSON")

    def branch(brand, containers, qlo, qhi, slo, shi):
        return ((j.p_brand == brand) & j.p_container.isin(containers)
                & (j.l_quantity >= qlo) & (j.l_quantity <= qhi)
                & (j.p_size >= slo) & (j.p_size <= shi))

    m = base & (
        branch("Brand#12", ["SM CASE", "SM BOX", "SM PACK", "SM PKG"], 1, 11, 1, 5)
        | branch("Brand#23", ["MED BAG", "MED BOX", "MED PKG", "MED PACK"], 10, 20, 1, 10)
        | branch("Brand#34", ["LG CASE", "LG BOX", "LG PACK", "LG PKG"], 20, 30, 1, 15))
    return pd.DataFrame({
        "revenue": [(j[m].l_extendedprice * (1 - j[m].l_discount)).sum()]})


ORACLES = {"q1": q1, "q3": q3, "q5": q5, "q6": q6, "q10": q10, "q12": q12,
           "q14": q14, "q19": q19}
