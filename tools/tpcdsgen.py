"""tpcds-lite: numpy generator for the TPC-DS tables the join-heavy subset
(q17/q25/q29, BASELINE.md config #5) touches. Same stance as tpchgen: the
pandas oracle runs over the SAME generated data, so simplified value
distributions are fine; what matters is the join topology — store_sales ⋈
store_returns on the composite (customer, item, ticket) key, a many-to-many
catalog_sales join, and three date_dim roles."""

from __future__ import annotations

import numpy as np

from cloudberry_tpu import types as T
from cloudberry_tpu.types import Schema, date_to_days

SCHEMAS: dict[str, Schema] = {
    "date_dim": Schema.of(d_date_sk=T.INT64, d_date=T.DATE, d_year=T.INT32,
                          d_moy=T.INT32, d_quarter_name=T.STRING,
                          d_week_seq=T.INT32, d_day_name=T.STRING),
    "item": Schema.of(i_item_sk=T.INT64, i_item_id=T.STRING,
                      i_item_desc=T.STRING, i_current_price=T.DECIMAL(2),
                      i_brand_id=T.INT32, i_brand=T.STRING,
                      i_class=T.STRING, i_category=T.STRING,
                      i_manufact_id=T.INT32, i_manager_id=T.INT32),
    "store": Schema.of(s_store_sk=T.INT64, s_store_id=T.STRING,
                       s_store_name=T.STRING, s_state=T.STRING),
    "customer": Schema.of(c_customer_sk=T.INT64, c_customer_id=T.STRING,
                          c_first_name=T.STRING, c_last_name=T.STRING,
                          c_current_addr_sk=T.INT64),
    "customer_address": Schema.of(ca_address_sk=T.INT64,
                                  ca_state=T.STRING, ca_zip=T.STRING),
    "time_dim": Schema.of(t_time_sk=T.INT64, t_hour=T.INT32),
    "web_page": Schema.of(wp_web_page_sk=T.INT64,
                          wp_char_count=T.INT32),
    "catalog_returns": Schema.of(cr_order_number=T.INT64,
                                 cr_return_amount=T.DECIMAL(2)),
    "web_returns": Schema.of(wr_order_number=T.INT64,
                             wr_return_amt=T.DECIMAL(2)),
    "store_sales": Schema.of(ss_sold_date_sk=T.INT64, ss_item_sk=T.INT64,
                             ss_customer_sk=T.INT64, ss_ticket_number=T.INT64,
                             ss_store_sk=T.INT64, ss_quantity=T.INT32,
                             ss_ext_sales_price=T.DECIMAL(2),
                             ss_net_profit=T.DECIMAL(2)),
    "store_returns": Schema.of(sr_returned_date_sk=T.INT64,
                               sr_item_sk=T.INT64, sr_customer_sk=T.INT64,
                               sr_ticket_number=T.INT64,
                               sr_return_quantity=T.INT32,
                               sr_net_loss=T.DECIMAL(2)),
    "catalog_sales": Schema.of(cs_sold_date_sk=T.INT64, cs_item_sk=T.INT64,
                               cs_bill_customer_sk=T.INT64,
                               cs_quantity=T.INT32,
                               cs_net_profit=T.DECIMAL(2),
                               cs_ext_sales_price=T.DECIMAL(2),
                               cs_order_number=T.INT64,
                               cs_warehouse_sk=T.INT64,
                               cs_ship_date_sk=T.INT64,
                               cs_ext_ship_cost=T.DECIMAL(2)),
    "web_sales": Schema.of(ws_sold_date_sk=T.INT64, ws_item_sk=T.INT64,
                           ws_bill_customer_sk=T.INT64,
                           ws_quantity=T.INT32,
                           ws_ext_sales_price=T.DECIMAL(2),
                           ws_net_profit=T.DECIMAL(2),
                           ws_order_number=T.INT64,
                           ws_warehouse_sk=T.INT64,
                           ws_ship_date_sk=T.INT64,
                           ws_ext_ship_cost=T.DECIMAL(2),
                           ws_web_page_sk=T.INT64,
                           ws_sold_time_sk=T.INT64),
    "warehouse": Schema.of(w_warehouse_sk=T.INT64,
                           w_warehouse_name=T.STRING),
    "inventory": Schema.of(inv_date_sk=T.INT64, inv_item_sk=T.INT64,
                           inv_warehouse_sk=T.INT64,
                           inv_quantity_on_hand=T.INT32),
}

DIST_KEYS = {
    "date_dim": None, "item": None, "store": None,      # replicated dims
    "warehouse": None, "customer_address": None, "time_dim": None,
    "web_page": None,
    "customer": ("c_customer_sk",),
    "store_sales": ("ss_ticket_number",),
    "store_returns": ("sr_ticket_number",),
    "catalog_sales": ("cs_bill_customer_sk",),
    "catalog_returns": ("cr_order_number",),
    "web_sales": ("ws_bill_customer_sk",),
    "web_returns": ("wr_order_number",),
    "inventory": ("inv_item_sk",),
}

_STATES = ["TN", "CA", "TX", "WA", "NY", "GA", "OH", "MI"]
_WORDS = ["bright", "quiet", "amber", "rustic", "mellow", "crisp", "vivid",
          "plain", "brass", "linen"]


def generate(scale: float = 1.0, seed: int = 0):
    rng = np.random.default_rng(seed)
    n_dates = 365 * 4                       # 1998-01-01 .. 2001-12-30
    n_item = max(int(500 * scale), 50)
    n_store = 12
    n_cust = max(int(2_000 * scale), 100)
    n_ss = max(int(30_000 * scale), 1_000)
    n_cs = max(int(20_000 * scale), 800)

    data: dict[str, dict[str, np.ndarray]] = {}

    base = date_to_days("1998-01-01")
    days = np.arange(n_dates, dtype=np.int64)
    dates = base + days
    years = 1998 + days // 365
    moy = (days % 365) // 31 + 1
    moy = np.clip(moy, 1, 12)
    _DAYNAMES = np.asarray(["Sunday", "Monday", "Tuesday", "Wednesday",
                            "Thursday", "Friday", "Saturday"],
                           dtype=object)
    data["date_dim"] = {
        "d_date_sk": days + 1,
        "d_date": dates,
        "d_year": years.astype(np.int32),
        "d_moy": moy.astype(np.int32),
        "d_quarter_name": np.asarray(
            [f"{y}Q{(m - 1) // 3 + 1}" for y, m in zip(years, moy)],
            dtype=object),
        # round-5 weekly columns (q43/q59): derived, no rng consumed.
        # 1998-01-01 was a Thursday; (dates + 4) % 7 == 0 on Sundays.
        "d_week_seq": ((days + 4) // 7 + 1).astype(np.int32),
        "d_day_name": _DAYNAMES[(dates + 4) % 7],
    }

    ik = np.arange(1, n_item + 1, dtype=np.int64)
    w = np.asarray(_WORDS, dtype=object)
    # round-4 reporting columns draw from their OWN stream: consuming the
    # shared rng here would shift every later table's draws and silently
    # re-tune the q17/q25/q29 filter selectivities
    rng2 = np.random.default_rng(seed + 104729)
    brand_id = rng2.integers(1, 12, n_item).astype(np.int32)
    classes = np.asarray(["alpha", "beta", "gamma", "delta"], dtype=object)
    cats = np.asarray(["Books", "Music", "Sports"], dtype=object)
    data["item"] = {
        "i_item_sk": ik,
        "i_item_id": np.asarray([f"ITEM{i:08d}" for i in ik], dtype=object),
        "i_item_desc": (w[rng.integers(0, 10, n_item)] + " "
                        + w[rng.integers(0, 10, n_item)]),
        "i_current_price": rng.integers(100, 10_000, n_item) / 100.0,
        "i_brand_id": brand_id,
        "i_brand": np.asarray([f"Brand#{b}" for b in brand_id],
                              dtype=object),
        "i_class": classes[rng2.integers(0, len(classes), n_item)],
        "i_category": cats[rng2.integers(0, len(cats), n_item)],
        "i_manufact_id": rng2.integers(1, 20, n_item).astype(np.int32),
        "i_manager_id": rng2.integers(1, 8, n_item).astype(np.int32),
    }

    sk = np.arange(1, n_store + 1, dtype=np.int64)
    data["store"] = {
        "s_store_sk": sk,
        "s_store_id": np.asarray([f"ST{i:06d}" for i in sk], dtype=object),
        "s_store_name": np.asarray([f"Store {i}" for i in sk], dtype=object),
        "s_state": np.asarray(_STATES, dtype=object)[
            rng.integers(0, len(_STATES), n_store)],
    }

    # round-5 customer identity + address columns on their OWN stream
    # (rng5): committed queries' selectivities are pinned to the existing
    # streams' draw sequences
    rng5 = np.random.default_rng(seed + 331337)
    n_ca = max(int(800 * scale), 80)
    firsts = np.asarray([f"First{i:02d}" for i in range(40)], dtype=object)
    lasts = np.asarray([f"Last{i:02d}" for i in range(60)], dtype=object)
    csk = np.arange(1, n_cust + 1, dtype=np.int64)
    data["customer"] = {
        "c_customer_sk": csk,
        "c_customer_id": np.asarray([f"CUST{i:09d}" for i in csk],
                                    dtype=object),
        "c_first_name": firsts[rng5.integers(0, len(firsts), n_cust)],
        "c_last_name": lasts[rng5.integers(0, len(lasts), n_cust)],
        "c_current_addr_sk": rng5.integers(1, n_ca + 1, n_cust)
        .astype(np.int64),
    }
    zips = np.asarray(
        [f"{p}{s:02d}" for p in ("850", "856", "859", "834", "772",
                                 "601", "331", "443")
         for s in range(25)], dtype=object)
    data["customer_address"] = {
        "ca_address_sk": np.arange(1, n_ca + 1, dtype=np.int64),
        "ca_state": np.asarray(_STATES, dtype=object)[
            rng5.integers(0, len(_STATES), n_ca)],
        "ca_zip": zips[rng5.integers(0, len(zips), n_ca)],
    }
    data["time_dim"] = {
        "t_time_sk": np.arange(1, 25, dtype=np.int64),
        "t_hour": np.arange(0, 24, dtype=np.int32),
    }
    n_wp = 10
    data["web_page"] = {
        "wp_web_page_sk": np.arange(1, n_wp + 1, dtype=np.int64),
        "wp_char_count": rng5.integers(1000, 9000, n_wp).astype(np.int32),
    }

    ss_date = rng.integers(1, n_dates + 1, n_ss)
    data["store_sales"] = {
        "ss_sold_date_sk": ss_date.astype(np.int64),
        "ss_item_sk": rng.integers(1, n_item + 1, n_ss).astype(np.int64),
        "ss_customer_sk": rng.integers(1, n_cust + 1, n_ss).astype(np.int64),
        "ss_ticket_number": np.arange(1, n_ss + 1, dtype=np.int64),
        "ss_store_sk": rng.integers(1, n_store + 1, n_ss).astype(np.int64),
        "ss_quantity": rng.integers(1, 100, n_ss).astype(np.int32),
        "ss_ext_sales_price": rng2.integers(100, 50_000, n_ss) / 100.0,
        "ss_net_profit": rng.integers(-5_000, 20_000, n_ss) / 100.0,
    }

    # ~35% of sales get returned within ~180 days
    ret_idx = np.sort(rng.choice(n_ss, size=int(n_ss * 0.35), replace=False))
    n_sr = len(ret_idx)
    sr_date = np.minimum(ss_date[ret_idx] + rng.integers(1, 180, n_sr),
                         n_dates)
    data["store_returns"] = {
        "sr_returned_date_sk": sr_date.astype(np.int64),
        "sr_item_sk": data["store_sales"]["ss_item_sk"][ret_idx],
        "sr_customer_sk": data["store_sales"]["ss_customer_sk"][ret_idx],
        "sr_ticket_number": data["store_sales"]["ss_ticket_number"][ret_idx],
        "sr_return_quantity": rng.integers(1, 50, n_sr).astype(np.int32),
        "sr_net_loss": rng.integers(50, 10_000, n_sr) / 100.0,
    }

    data["catalog_sales"] = {
        "cs_sold_date_sk": rng.integers(1, n_dates + 1, n_cs).astype(np.int64),
        "cs_item_sk": rng.integers(1, n_item + 1, n_cs).astype(np.int64),
        "cs_bill_customer_sk": rng.integers(1, n_cust + 1, n_cs)
        .astype(np.int64),
        "cs_quantity": rng.integers(1, 100, n_cs).astype(np.int32),
        "cs_net_profit": rng.integers(-5_000, 20_000, n_cs) / 100.0,
        # round-4 q20 column on its own stream: committed queries'
        # selectivities are pinned to the EXISTING streams' draw
        # sequences, so new columns never touch them
        "cs_ext_sales_price": np.random.default_rng(seed + 424243)
        .integers(100, 50_000, n_cs) / 100.0,
    }
    # round-5 fulfillment columns (q16/q99) on their own stream: orders
    # group ~3 lines; ~20% of lines ship from a second warehouse
    rng6 = np.random.default_rng(seed + 550551)
    n_ords = max(n_cs // 3, 1)
    cs_ord = rng6.integers(1, n_ords + 1, n_cs).astype(np.int64)
    data["catalog_sales"]["cs_order_number"] = cs_ord
    wh_of_order = rng6.integers(1, 5, n_ords + 1)
    cs_wh = wh_of_order[cs_ord]
    flip = rng6.random(n_cs) < 0.2
    cs_wh = np.where(flip, cs_wh % 4 + 1, cs_wh)
    data["catalog_sales"]["cs_warehouse_sk"] = cs_wh.astype(np.int64)
    data["catalog_sales"]["cs_ship_date_sk"] = np.minimum(
        data["catalog_sales"]["cs_sold_date_sk"]
        + rng6.integers(1, 150, n_cs), n_dates).astype(np.int64)
    data["catalog_sales"]["cs_ext_ship_cost"] = \
        rng6.integers(50, 5_000, n_cs) / 100.0
    ret_orders = rng6.choice(np.arange(1, n_ords + 1),
                             size=max(n_ords // 5, 1), replace=False)
    data["catalog_returns"] = {
        "cr_order_number": np.sort(ret_orders).astype(np.int64),
        "cr_return_amount": rng6.integers(100, 20_000,
                                          len(ret_orders)) / 100.0,
    }

    # web/inventory family (q12/q21/q86): OWN rng streams — consuming the
    # shared one would shift earlier tables' draws and silently re-tune
    # the committed queries' filter selectivities
    rng3 = np.random.default_rng(seed + 224737)
    n_ws = max(int(15_000 * scale), 600)
    data["web_sales"] = {
        "ws_sold_date_sk": rng3.integers(1, n_dates + 1, n_ws)
        .astype(np.int64),
        "ws_item_sk": rng3.integers(1, n_item + 1, n_ws).astype(np.int64),
        "ws_bill_customer_sk": rng3.integers(1, n_cust + 1, n_ws)
        .astype(np.int64),
        "ws_quantity": rng3.integers(1, 100, n_ws).astype(np.int32),
        "ws_ext_sales_price": rng3.integers(100, 50_000, n_ws) / 100.0,
        "ws_net_profit": rng3.integers(-5_000, 20_000, n_ws) / 100.0,
    }
    # round-5 web fulfillment columns (q90/q94) on their own stream
    rng7 = np.random.default_rng(seed + 770771)
    n_words = max(n_ws // 3, 1)
    ws_ord = rng7.integers(1, n_words + 1, n_ws).astype(np.int64)
    data["web_sales"]["ws_order_number"] = ws_ord
    wwh = rng7.integers(1, 5, n_words + 1)
    ws_wh = wwh[ws_ord]
    wflip = rng7.random(n_ws) < 0.2
    data["web_sales"]["ws_warehouse_sk"] = np.where(
        wflip, ws_wh % 4 + 1, ws_wh).astype(np.int64)
    data["web_sales"]["ws_ship_date_sk"] = np.minimum(
        data["web_sales"]["ws_sold_date_sk"]
        + rng7.integers(1, 150, n_ws), n_dates).astype(np.int64)
    data["web_sales"]["ws_ext_ship_cost"] = \
        rng7.integers(50, 5_000, n_ws) / 100.0
    data["web_sales"]["ws_web_page_sk"] = \
        rng7.integers(1, 11, n_ws).astype(np.int64)
    data["web_sales"]["ws_sold_time_sk"] = \
        rng7.integers(1, 25, n_ws).astype(np.int64)
    wret = rng7.choice(np.arange(1, n_words + 1),
                       size=max(n_words // 5, 1), replace=False)
    data["web_returns"] = {
        "wr_order_number": np.sort(wret).astype(np.int64),
        "wr_return_amt": rng7.integers(100, 20_000, len(wret)) / 100.0,
    }
    n_wh = 4
    data["warehouse"] = {
        "w_warehouse_sk": np.arange(1, n_wh + 1, dtype=np.int64),
        "w_warehouse_name": np.asarray(
            [f"Warehouse {i}" for i in range(1, n_wh + 1)], dtype=object),
    }
    n_inv = max(int(25_000 * scale), 1_000)
    data["inventory"] = {
        "inv_date_sk": rng3.integers(1, n_dates + 1, n_inv)
        .astype(np.int64),
        "inv_item_sk": rng3.integers(1, n_item + 1, n_inv)
        .astype(np.int64),
        "inv_warehouse_sk": rng3.integers(1, n_wh + 1, n_inv)
        .astype(np.int64),
        "inv_quantity_on_hand": rng3.integers(0, 1_000, n_inv)
        .astype(np.int32),
    }
    return data


def load_tpcds(session, scale: float = 1.0, seed: int = 0) -> None:
    from tools.tpchgen import load_tables

    load_tables(session, SCHEMAS, DIST_KEYS, generate(scale, seed))
