"""Closed-loop serving benchmark — QPS/latency for the micro-batch
dispatcher vs one-at-a-time dispatch (the ISSUE-3 acceptance harness).

N simulated clients hammer a Server over the wire protocol with a
statement mix; each mode runs the SAME closed loop and the CSV rows make
the comparison direct:

    mode,mix,clients,duration_s,requests,qps,p50_ms,p99_ms,compiles,\
dispatches,batches,batched_requests,avg_occupancy,deadline_misses,\
cancels,recovery_count,tiles_replayed,recovery_ms,tenant,tenant_qps,\
tenant_p50_ms,tenant_p99_ms,tenant_queue_depth,fairness_index

Small runs drive one OS thread per client; large runs (or any --tenants
run) multiplex the clients over a few selector driver threads, each
connection an INDEPENDENT closed loop — that is how the bench sustains
1000+ simulated clients against the event-loop serving core
(serve/asyncore.py). With --tenants, requests carry tenant names, the
server schedules them deficit-weighted-round-robin (sched/tenancy.py),
and each tenant gets its own CSV row (per-tenant QPS / p50 / p99 /
peak queue depth) under the aggregate's fairness_index (Jain's index
over weight-normalized picks; 1.0 = throughput exactly proportional to
weight).

- ``direct``  — dispatcher off: every request is its own parse→(generic
  rebind)→launch through the shared session.
- ``batched`` — dispatcher on (config.sched.enabled): same-skeleton
  requests coalesce per tick into one stacked vmapped launch.

Mixes:
- ``point`` — repeated point lookups with rotating literals
  (``SELECT k, v, w FROM pts WHERE k = <r>``): the prepared-statement
  serving shape; generic plans make it compile-free, the dispatcher makes
  it launch-amortized.
- ``q6``    — a parameterized TPC-H-Q6-shaped aggregate over a synthetic
  lineitem slice with rotating predicate literals.
- ``mixed`` — 80% point / 20% q6.
- ``coldscan`` — 1-in-8 requests run a long COLD tiled aggregate (the
  catalog is store-backed and the budget shrunk, so ``li`` streams
  micro-partition files through the scan pipeline, exec/scanpipe.py)
  while the rest stay point lookups: the multi-tenant starvation case —
  long out-of-core statements competing with latency-sensitive points.
  Pair with --tenants to read the fairness columns under it.
- ``hotcold`` — the HBM buffer-pool serving workload (ISSUE 16): a
  store-backed HOT table scanned by the SAME tiled aggregate on most
  requests (from the third scan the pool serves its tiles from device
  memory at zero host reads/decodes) against a same-shape COLD table
  scanned with rotating literals under a pool budget sized to hold only
  the hot set (the cold set is refused over evicting hotter, then
  churns). The bufpool_hit_rate / host_decodes CSV columns report the
  run's counter deltas, and an after-window probe times one pool-warm
  hot scan vs one cold scan on the same container size — printed as a
  rows/s comparison with the hot probe's host-decode count (zero when
  the claim holds).
- ``readwrite`` — the write-plane workload (ISSUE 18): 1-in-4 requests
  are wire-level APPENDs into a store-backed table through the
  streaming ingest plane (group-committed INSERT flushes) while the
  rest stay point lookups, with the background compaction service
  enabled and folding the append debt DURING the measured window. The
  ingest_qps / flush_ms_p95 / compact_chunks / delta_parts_max CSV
  columns report the write plane's side of the run; the read QPS
  column is the bench's pin that foreground serving holds up while
  compaction runs.

Runs on CPU (JAX_PLATFORMS=cpu) for CI smoke; on real hardware the launch
amortization grows with dispatch overhead. Usage:

    python tools/serve_bench.py --mode both --mix point --clients 8 \
        --duration 5 --csv out.csv
"""

from __future__ import annotations

import argparse
import json
import os
import selectors
import socket
import sys
import tempfile
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

CSV_HEADER = ("mode,mix,clients,duration_s,requests,qps,p50_ms,p99_ms,"
              "compiles,dispatches,batches,batched_requests,avg_occupancy,"
              "deadline_misses,cancels,recovery_count,tiles_replayed,"
              "recovery_ms,tenant,tenant_qps,tenant_p50_ms,tenant_p99_ms,"
              "tenant_queue_depth,fairness_index,"
              # ISSUE 9: server-side latency percentiles from the obs
              # registry's statement_seconds histogram (engine clocks,
              # not client clocks) + per-stage time shares + sampled
              # trace span counts
              "srv_p50_ms,srv_p95_ms,srv_p99_ms,queue_wait_share,"
              "compile_share,launch_share,render_share,trace_spans,"
              # ISSUE 12 (capacity & forensics plane): flight-recorder
              # captures over the run (--slow-ms arms the threshold),
              # skew alarms from the motion telemetry, and the peak
              # per-statement device-byte estimate
              "flight_captures,skew_events,peak_stmt_mb,"
              # ISSUE 13 (online topology changes): --expand-at /
              # --shrink-at land an epoch-versioned resize mid-load —
              # cutover wall clock, rows the background rebalancer
              # moved (jump-hash minimal delta), and epoch flips over
              # the run (failover promotions included)
              "cutover_ms,moved_rows,epoch_flips,"
              # ISSUE 16 (HBM buffer pool): pool hit rate over the
              # run's store scans (bufpool_hits / lookups) and host
              # decode count — under --mix hotcold the hot set's
              # repeats are served from device memory, so decodes
              # track the COLD set only
              "bufpool_hit_rate,host_decodes,"
              # ISSUE 17 (feedback-driven re-optimization):
              # mid-statement adaptive replans taken over the window
              # and capacity rungs the learned sketches priced down
              # from the static estimate on repeat statements
              "adaptive_replans,rung_downgrades,"
              # ISSUE 18 (write plane): appends/s accepted by the
              # streaming ingest buffers over the window, the p95 group
              # flush commit latency, compaction chunks folded DURING
              # the run, and the post-run bounded-invariant census
              # (worst per-table delta-partition count)
              "ingest_qps,flush_ms_p95,compact_chunks,delta_parts_max,"
              # ISSUE 19 (crash-only storage): --kill-at SEAM runs one
              # process-kill torture pass (tools/crash_torture.py) —
              # recovery_ms carries restart-to-first-answer wall clock
              # and acked_lost MUST be 0 (acked writes survive the
              # kill). Normal bench rows report acked_lost=0.
              "acked_lost,"
              # ISSUE 20 (windowed tile dispatch, exec/tilepipe.py):
              # checks that fired after newer tiles were already in
              # flight, and the window replays those deferrals cost
              "tile_deferred_overflows,tile_window_replays")


def parse_tenantspec(spec: str, clients: int):
    """'gold:3,silver:1' → [TenantSpec, ...]; per-field form is
    name:weight[:max_concurrency[:max_queue]]. The default queue depth
    scales with the client count so a closed-loop bench saturates the
    SCHEDULER (the fairness story), not the admission refusal."""
    from cloudberry_tpu.config import TenantSpec

    out = []
    for part in spec.split(","):
        if not part.strip():
            continue
        bits = part.strip().split(":")
        # the scheduler lowercases group names — match it here so the
        # per-tenant snapshot lookups (queue depth) resolve
        name = bits[0].lower()
        weight = int(bits[1]) if len(bits) > 1 else 1
        conc = int(bits[2]) if len(bits) > 2 else 0
        queue = int(bits[3]) if len(bits) > 3 else max(256, clients * 2)
        out.append(TenantSpec(name=name, weight=weight,
                              max_concurrency=conc, max_queue=queue))
    return out


def build_session(mode: str, rows: int, tick_s: float, max_batch: int,
                  mix: str = "point", chaos: float = 0.0,
                  tenants=None, server_core: str = "async",
                  clients: int = 16, aging_s: float = None,
                  trace_sample: int = 0, slow_ms: float = None,
                  segments: int = 1, compact_off: bool = False):
    import numpy as np

    import cloudberry_tpu as cb
    from cloudberry_tpu.config import Config

    over = {
        "sched.enabled": mode == "batched",
        "sched.tick_s": tick_s,
        "sched.max_batch": max_batch,
        "serve.threaded": server_core == "threaded",
        "n_segments": max(1, segments),
    }
    if clients > 64:
        # warehouse-concurrency closed loop: the global dispatcher queue
        # must hold every in-flight client
        over["sched.max_queue"] = max(256, clients * 2)
    if tenants:
        over["tenancy.enabled"] = True
        over["tenancy.tenants"] = tuple(tenants)
        if aging_s is not None:
            # the weights-vs-tail dial: queues deeper than aging_s's
            # wait turn DWRR into oldest-first (bounded p99, flattened
            # ratio) — raise it when the ratio is what you measure
            over["tenancy.aging_s"] = aging_s
    if mix == "spill":
        # the chaos workload streams tiles: shrink the budget so the li
        # aggregate runs through the tiled (checkpointable) path
        over["resource.query_mem_bytes"] = 1 << 20
    if mix == "coldscan":
        # long COLD tiled scans competing with point lookups: back the
        # catalog with a store and shrink the budget so li streams
        # micro-partition files through the scan pipeline; a FRESH
        # session binds below (set_data leaves tables warm in the
        # loading session). pts stays small enough to dispatch direct.
        over["storage.root"] = tempfile.mkdtemp(
            prefix="cbtpu_servebench_cold_")
        over["resource.query_mem_bytes"] = 2 << 20
    if mix == "hotcold":
        # the buffer-pool serving workload: li (hot) and lc (cold) are
        # the same store-backed shape; the pool budget holds the hot
        # statement's two scanned columns (~2MB at 120k rows) with a
        # little slack but NOT both tables, so the hot set goes
        # device-resident while the cold set is refused over evicting
        # hotter entries and churns in the remainder
        over["storage.root"] = tempfile.mkdtemp(
            prefix="cbtpu_servebench_hot_")
        over["resource.query_mem_bytes"] = 2 << 20
        over["bufferpool.max_bytes"] = 3 << 20
    if mix == "readwrite":
        # the write-plane workload: every table store-backed, the ingest
        # buffers tuned so a closed loop's appends group-commit visibly,
        # and the compaction service folding the debt DURING the window
        # (tight interval, low invariant threshold, small partitions so
        # small flushed tails actually accumulate census)
        over["storage.root"] = tempfile.mkdtemp(
            prefix="cbtpu_servebench_rw_")
        over["storage.rows_per_partition"] = 4096
        over["ingest.flush_rows"] = 128
        over["ingest.flush_ms"] = 5.0
        # --no-compact is the A/B baseline for the acceptance claim
        # ("read QPS holds while compaction runs"): same closed loop,
        # same append share, debt just accumulates unfolded
        over["compact.enabled"] = not compact_off
        over["compact.interval_s"] = 0.25
        over["compact.max_delta_parts"] = 8
    if chaos > 0:
        # probabilistic device loss compounds per tile: give recovery
        # more re-dispatches than the default flap allowance
        over["health.retries"] = 4
    if trace_sample:
        # --trace-sample N: keep every Nth statement's span tree; the
        # run dumps the ring as ONE perfetto-loadable file at the end
        over["obs.trace_sample"] = max(1, trace_sample)
        over["obs.trace_ring"] = 512
    if slow_ms is not None:
        # --slow-ms N: arm the flight recorder at this threshold so the
        # run's slow-statement captures show up in the CSV
        over["obs.slow_ms"] = float(slow_ms)
    cfg = Config().with_overrides(**over)
    s = cb.Session(cfg)
    # coldscan sizing: pts small enough to stay under the shrunken
    # budget (point lookups must dispatch direct), li big enough that
    # the cold aggregate streams several tiles per statement
    n_pts = min(rows, _COLD_PTS_ROWS) \
        if mix in ("coldscan", "hotcold") else rows
    s.sql("create table pts (k bigint, v bigint, w double) "
          "distributed by (k)")
    t = s.catalog.table("pts")
    t.set_data({
        "k": np.arange(n_pts, dtype=np.int64),
        "v": (np.arange(n_pts, dtype=np.int64) * 7) % 1000,
        "w": np.arange(n_pts, dtype=np.float64) * 0.5,
    }, {})
    s.sql("create table li (qty decimal(2), price decimal(2), "
          "disc decimal(2), sd date)")
    rng = np.random.default_rng(11)
    m = max(rows * 2, 120_000) if mix in ("coldscan", "hotcold") \
        else max(rows // 2, 1024)
    s.catalog.table("li").set_data({
        "qty": rng.integers(1, 5000, m).astype(np.int64),
        "price": rng.integers(100, 10000, m).astype(np.int64),
        "disc": rng.integers(0, 11, m).astype(np.int64),
        "sd": rng.integers(8000, 12000, m).astype(np.int32),
    }, {})
    if mix == "hotcold":
        # the COLD container: identical schema and row count as li so
        # the after-window rows/s probe compares pool-served vs
        # host-decoded scans of the SAME shape
        s.sql("create table lc (qty decimal(2), price decimal(2), "
              "disc decimal(2), sd date)")
        s.catalog.table("lc").set_data({
            "qty": rng.integers(1, 5000, m).astype(np.int64),
            "price": rng.integers(100, 10000, m).astype(np.int64),
            "disc": rng.integers(0, 11, m).astype(np.int64),
            "sd": rng.integers(8000, 12000, m).astype(np.int32),
        }, {})
    if mix == "readwrite":
        # the append target: store-backed with a committed base, so
        # compaction has a manifest to fold the flushed tails into
        s.sql("create table ing (k bigint, v bigint) distributed by (k)")
        s.catalog.table("ing").set_data({
            "k": np.arange(4096, dtype=np.int64),
            "v": np.zeros(4096, dtype=np.int64)}, {})
        s._servebench_root = cfg.storage.root
    if mix in ("coldscan", "hotcold"):
        s = cb.Session(cfg)  # fresh bind: tables come up cold
        s._servebench_root = cfg.storage.root
        s._servebench_rows = m
    return s


def _point_sql(i: int, rows: int) -> str:
    return f"select k, v, w from pts where k = {(i * 2654435761) % rows}"


def _q6_sql(i: int) -> str:
    lo = 1 + (i % 5)
    return ("select sum(price * disc) as rev from li "
            f"where disc between 0.0{lo} and 0.0{lo + 4} "
            f"and qty < {20 + (i % 7)}.0")


def _spill_sql(i: int) -> str:
    # a tiled (out-of-core) aggregate with rotating literals: under the
    # shrunken spill-mix budget this statement streams tiles through the
    # checkpoint seams — the --chaos recovery workload
    return ("select sum(price) as sp, count(*) as c from li "
            f"where qty < {4000 + (i % 50)}.0")


def _hot_sql() -> str:
    # IDENTICAL every time: the same statement re-scans the same tiles,
    # so from the third scan the buffer pool serves it from device
    # memory (admit_min_scans=2; the warmup scan counts as the first)
    return "select sum(price) as sp, count(*) as c from li " \
           "where qty < 4000.0"


def _cold_sql(i: int) -> str:
    # same shape/size container as the hot statement but a rotating
    # literal over lc — whose tiles never fit the hotcold pool budget
    # next to li's, so every scan pays host read+decode
    return ("select sum(price) as sp, count(*) as c from lc "
            f"where qty < {4000 + (i % 50)}.0")


# coldscan keeps pts small so point lookups dispatch direct under the
# shrunken tiled budget; _mix_sql caps the key range to match
_COLD_PTS_ROWS = 10_000


def _is_append(mix: str, i: int) -> bool:
    # readwrite: every 4th request is a wire-level APPEND — the drivers
    # branch on this BEFORE asking _mix_sql for a statement
    return mix == "readwrite" and i % 4 == 3


def _append_req(i: int) -> dict:
    return {"append": {"table": "ing",
                       "rows": [[1_000_000 + i, i % 97]]}}


def _mix_sql(mix: str, i: int, rows: int) -> str:
    if mix == "readwrite":
        return _point_sql(i, rows)
    if mix == "point":
        return _point_sql(i, rows)
    if mix == "q6":
        return _q6_sql(i)
    if mix == "spill":
        return _spill_sql(i)
    if mix == "coldscan":
        # 1-in-8 long cold tiled scans (same statement shape as spill,
        # but li is store-backed: every run re-streams and re-decodes
        # its micro-partitions through the scan pipeline) against a
        # majority of latency-sensitive point lookups
        return (_spill_sql(i) if i % 8 == 7
                else _point_sql(i, min(rows, _COLD_PTS_ROWS)))
    if mix == "hotcold":
        # 6-in-8 hot (identical, pool-served once admitted) against
        # 2-in-8 cold rotating scans: the 3:1 scan-frequency gap is
        # what keeps the hot set winning the refusal-over-evicting-
        # hotter comparison
        return _cold_sql(i) if i % 8 in (3, 7) else _hot_sql()
    return _q6_sql(i) if i % 5 == 4 else _point_sql(i, rows)


_BACKPRESSURE_ETYPES = ("TenantQueueFull", "SchedQueueFull", "ServerBusy",
                        "IngestQueueFull")


def _mux_driver(wid: int, n_conns: int, first_idx: int, host, port,
                mix: str, rows: int, tenant_names, stop_at, lat_map,
                lat_lock, rejects, errors, reads):
    """One driver thread simulating ``n_conns`` independent closed-loop
    clients: a selector loop sends each connection's next request the
    moment its previous response lands, so per-tenant throughput under
    saturation reflects the SERVER's scheduling (a lock-step
    send-all/recv-all cycle would equalize tenants by construction)."""
    sel = selectors.DefaultSelector()
    conns = []
    local: dict = {}
    rej_local = 0
    reads_local = 0
    try:
        for j in range(n_conns):
            idx = first_idx + j
            s = socket.create_connection((host, port), timeout=120)
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            r = s.makefile("rb")
            w = s.makefile("wb")
            tenant = tenant_names[idx % len(tenant_names)] \
                if tenant_names else None
            rec = {"s": s, "r": r, "w": w, "tenant": tenant,
                   "i": idx * 100_003, "t0": 0.0}
            conns.append(rec)
            sel.register(s, selectors.EVENT_READ, rec)
            local.setdefault(tenant, [])

        def send_next(rec):
            rec["ap"] = _is_append(mix, rec["i"])
            req = _append_req(rec["i"]) if rec["ap"] \
                else {"sql": _mix_sql(mix, rec["i"], rows)}
            if rec["tenant"]:
                req["tenant"] = rec["tenant"]
            rec["i"] += 1
            rec["t0"] = time.monotonic()
            rec["w"].write(json.dumps(req).encode() + b"\n")
            rec["w"].flush()

        for rec in conns:
            send_next(rec)
        while time.monotonic() < stop_at[0]:
            for key, _ in sel.select(timeout=0.1):
                rec = key.data
                line = rec["r"].readline()
                if not line:
                    raise RuntimeError("server closed a bench connection")
                resp = json.loads(line)
                dt = time.monotonic() - rec["t0"]
                if resp.get("ok"):
                    local[rec["tenant"]].append(dt)
                    if not rec.get("ap"):
                        reads_local += 1
                elif resp.get("etype") in _BACKPRESSURE_ETYPES:
                    # retryable refusal: counted as BACKPRESSURE (its
                    # own metric — NOT a deadline miss), loop retries
                    rej_local += 1
                else:
                    raise RuntimeError(resp.get("error", "bench error"))
                if time.monotonic() < stop_at[0]:
                    send_next(rec)
    except Exception as e:  # pragma: no cover - surfaced in result
        errors.append(f"{type(e).__name__}: {e}")
    finally:
        for rec in conns:
            try:
                rec["s"].close()
            except OSError:
                pass
        sel.close()
    with lat_lock:
        rejects[0] += rej_local
        reads[0] += reads_local
        for tenant, lats in local.items():
            lat_map.setdefault(tenant, []).extend(lats)


def _pct(lats, p: float) -> float:
    if not lats:
        return 0.0
    return lats[min(len(lats) - 1, int(p * len(lats)))] * 1000


def _stage_shares(registry) -> tuple[dict, int]:
    """(per-stage time shares, sampled span count) from the obs
    registry: each stage_seconds.<stage> histogram's SUM over the total
    across stages — where a served statement's time actually went,
    measured server-side."""
    snap = registry.snapshot()
    hists = snap.get("histograms", {})
    sums = {name.split(".", 1)[1]: h["sum"]
            for name, h in hists.items()
            if name.startswith("stage_seconds.")}
    total = sum(sums.values()) or 1.0
    shares = {f"{k}_share": round(v / total, 4) for k, v in sums.items()}
    spans = snap.get("counters", {}).get("trace_statements", 0)
    return shares, spans


def _hotcold_probe(session) -> dict:
    """After the measured window closes: time ONE pool-warm hot scan
    against ONE cold scan of the same-size container, each with its
    host_decodes counter delta — the bench's direct pin that the hot
    set is served with ZERO host reads/decodes (a counter fact, not a
    clock fact) and at measurably higher rows/s than the cold path.
    Rides as non-CSV extras (underscore keys) + a stderr summary."""
    log = session.stmt_log
    m = getattr(session, "_servebench_rows", 0)
    # settle scan: guarantees the hot set is past admission (scan 3+)
    # even if a very short window only reached it once
    session.sql(_hot_sql())
    out = {}
    for name, sql in (("hot", _hot_sql()), ("cold", _cold_sql(2))):
        d0 = log.counter("host_decodes")
        t0 = time.monotonic()
        session.sql(sql)
        wall = time.monotonic() - t0
        out[f"_{name}_rows_per_s"] = int(m / wall) if wall > 0 else 0
        out[f"_{name}_host_decodes"] = log.counter("host_decodes") - d0
    return out


def run_mode(mode: str, mix: str, clients: int, duration_s: float,
             rows: int, tick_s: float, max_batch: int,
             cancel_mix: float = 0.0, deadline_s: float = 0.005,
             chaos: float = 0.0, tenants=None,
             server_core: str = "async",
             driver_threads: int = 16, aging_s: float = None,
             trace_sample: int = 0, trace_out: str = None,
             slow_ms: float = None, segments: int = 1,
             expand_at=None, shrink_at=None,
             compact_off: bool = False) -> dict:
    """One closed-loop run; returns the CSV row fields.

    ``cancel_mix``: fraction of requests carrying a TIGHT per-request
    deadline (``deadline_s``) — the statement-lifecycle workload. Those
    that miss fail with the retryable timeout taxonomy (StatementTimeout
    / SchedDeadline) and count as ``deadline_misses``, not errors; the
    ``cancels`` column reports the engine's cancellation counters
    (cancel verb + watchdog) over the run.

    ``chaos``: per-hit device-loss probability armed on the dispatch and
    tile seams (utils/faultinject probabilistic arms) — the recovery
    workload. The recovery_count / tiles_replayed / recovery_ms columns
    report what the engine's checkpointed re-execution actually did;
    pair with ``--mix spill`` so statements stream tiles worth
    resuming."""
    from cloudberry_tpu.serve import Client, Server, ServerError
    from cloudberry_tpu.utils import faultinject as FI

    session = build_session(mode, rows, tick_s, max_batch,
                            mix=mix, chaos=chaos, tenants=tenants,
                            server_core=server_core, clients=clients,
                            aging_s=aging_s, trace_sample=trace_sample,
                            slow_ms=slow_ms, segments=segments,
                            compact_off=compact_off)
    # warm the compile caches OUTSIDE the measured window: the bench
    # compares steady-state dispatch, not first-compile latency
    session.sql(_point_sql(0, rows))
    session.sql(_q6_sql(0))
    if mix in ("spill", "coldscan"):
        session.sql(_spill_sql(0))
    if mix == "hotcold":
        # compiles both scan shapes outside the window; the hot warmup
        # is also the pool's FIRST observed scan (frequency 1), so the
        # measured window opens exactly one scan short of admission
        session.sql(_hot_sql())
        session.sql(_cold_sql(0))
    c_before = session.stmt_log.counter("compiles")
    d_before = session.stmt_log.counter("dispatches")
    x_before = (session.stmt_log.counter("cancel_requests")
                + session.stmt_log.counter("watchdog_timeouts"))
    r_before = session.stmt_log.counter("recoveries")
    tr_before = session.stmt_log.counter("tiles_replayed")
    rw_before = session.stmt_log.counter("recovery_wall_ms")
    fl_before = session.stmt_log.counter("flight_captures")
    sk_before = session.stmt_log.counter("skew_events")
    ef_before = session.stmt_log.counter("epoch_flips")
    mr_before = session.stmt_log.counter("topo_moved_rows")
    bh_before = session.stmt_log.counter("bufpool_hits")
    bm_before = session.stmt_log.counter("bufpool_misses")
    hd_before = session.stmt_log.counter("host_decodes")
    ar_before = session.stmt_log.counter("adaptive_replans")
    rd_before = session.stmt_log.counter("rung_downgrades")
    ia_before = session.stmt_log.counter("ingest_appends")
    cc_before = session.stmt_log.counter("compact_chunks")
    do_before = session.stmt_log.counter("tile_deferred_overflows")
    wr_before = session.stmt_log.counter("tile_window_replays")

    _MISS_ETYPES = ("StatementTimeout", "StatementCancelled",
                    "SchedDeadline")
    # a chaos run's residual losses (retries exhausted under the armed
    # device-loss rate) are the workload working, not bench failures
    _CHAOS_ETYPES = ("InjectedFault", "XlaRuntimeError")
    lats: list[float] = []
    misses = [0]
    lat_lock = threading.Lock()
    errors: list[str] = []
    stop_at = [0.0]
    stride = max(1, int(round(1.0 / cancel_mix))) if cancel_mix else 0

    def worker(wid: int):
        lat_local = []
        miss_local = 0
        reads_local = 0
        try:
            with Client(srv.host, srv.port) as c:
                i = wid * 100_003
                while time.monotonic() < stop_at[0]:
                    ap = _is_append(mix, i)
                    sql = None if ap else _mix_sql(mix, i, rows)
                    dl = deadline_s if stride and i % stride == 0 else None
                    t0 = time.monotonic()
                    try:
                        if ap:
                            c.append("ing", _append_req(i)["append"]["rows"])
                        else:
                            c.sql(sql, deadline_s=dl)
                            reads_local += 1
                    except ServerError as e:
                        # a deadlined request missing its deadline is the
                        # workload working, not a bench failure
                        if dl is not None and e.etype in _MISS_ETYPES:
                            miss_local += 1
                        elif e.etype in _BACKPRESSURE_ETYPES:
                            pass  # retryable refusal; the loop retries
                        elif chaos and e.etype in _CHAOS_ETYPES:
                            pass
                        else:
                            raise
                    i += 1
                    lat_local.append(time.monotonic() - t0)
        except Exception as e:  # pragma: no cover - surfaced in result
            errors.append(f"{type(e).__name__}: {e}")
        with lat_lock:
            lats.extend(lat_local)
            misses[0] += miss_local
            reads[0] += reads_local

    if chaos > 0:
        FI.inject_fault("tile_device_lost", "error", p=chaos, seed=1234)
        FI.inject_fault("exec_device_lost", "error", p=chaos, seed=4321)
    # mid-load topology chaos (--expand-at/--shrink-at "T:N"): a control
    # thread lands an epoch-versioned online resize T seconds into the
    # measured window while the clients keep hammering — the cutover_ms
    # / moved_rows / epoch_flips columns report what it cost
    topo_events = []
    for spec in ((("expand", expand_at),) if expand_at else ()) + \
            ((("shrink", shrink_at),) if shrink_at else ()):
        topo_events.append(spec)
    cutover_ms = [0.0]
    topo_errors: list[str] = []

    def _topo_driver():
        t_base = time.monotonic()
        for _, (at_s, target) in sorted(topo_events,
                                        key=lambda e: e[1][0]):
            delay = t_base + at_s - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            if time.monotonic() >= stop_at[0]:
                return
            try:
                out = session._topology.online_resize(target)
                cutover_ms[0] += out["cutover_ms"]
            except Exception as e:  # noqa: BLE001 — surfaced after run
                topo_errors.append(f"{type(e).__name__}: {e}")
                return
    lat_map: dict = {}
    rejects = [0]  # backpressure refusals (mux driver) — own metric
    reads = [0]    # successful READ requests (the readwrite split)
    tenant_names = [t.name for t in tenants] if tenants else None
    # driver choice: one OS thread per client stays exact for small runs
    # (and the cancel-mix workload needs per-request deadlines); past
    # that — or whenever tenants are declared — a few selector driver
    # threads each multiplex many independent closed-loop connections,
    # which is how the bench sustains 1k+ simulated clients
    mux = tenants is not None or clients > 32
    with Server(session=session) as srv:
        stop_at[0] = time.monotonic() + duration_s
        if mux:
            nthreads = min(driver_threads, clients)
            per = (clients + nthreads - 1) // nthreads
            threads = []
            first = 0
            for i in range(nthreads):
                n = min(per, clients - first)
                if n <= 0:
                    break
                threads.append(threading.Thread(
                    target=_mux_driver,
                    args=(i, n, first, srv.host, srv.port, mix, rows,
                          tenant_names, stop_at, lat_map, lat_lock,
                          rejects, errors, reads)))
                first += n
        else:
            threads = [threading.Thread(target=worker, args=(i,))
                       for i in range(clients)]
        t_start = time.monotonic()
        topo_thread = None
        if topo_events:
            topo_thread = threading.Thread(target=_topo_driver,
                                           daemon=True)
            topo_thread.start()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=duration_s + 120)
        if topo_thread is not None:
            topo_thread.join(timeout=60)
        wall = time.monotonic() - t_start
        disp = session.stmt_log
        dsnap = getattr(session, "_dispatcher", None)
        dstats = dsnap.snapshot() if dsnap is not None else {}
        tsnap = srv.tenancy.snapshot() if srv.tenancy is not None else {}
        fidx = srv.tenancy.fairness_index() \
            if srv.tenancy is not None else 1.0
    if chaos > 0:
        FI.reset_fault("tile_device_lost")
        FI.reset_fault("exec_device_lost")
    # the store root must outlive the counter reads AND the hotcold
    # probe below (which re-scans the store after the window closes)
    root = getattr(session, "_servebench_root", None)

    def _cleanup():
        if root:
            import shutil

            shutil.rmtree(root, ignore_errors=True)
    if errors:
        _cleanup()
        raise RuntimeError(f"bench clients failed: {errors[:3]}")
    if topo_errors:
        _cleanup()
        raise RuntimeError(f"topology chaos failed: {topo_errors}")
    if not mux:
        lat_map[None] = lats
    all_lats = sorted(x for ls in lat_map.values() for x in ls)

    out = {
        "mode": mode, "mix": mix, "clients": clients,
        "duration_s": round(wall, 2), "requests": len(all_lats),
        "qps": round(len(all_lats) / max(wall, 1e-9), 1),
        "p50_ms": round(_pct(all_lats, 0.50), 3),
        "p99_ms": round(_pct(all_lats, 0.99), 3),
        "compiles": disp.counter("compiles") - c_before,
        "dispatches": disp.counter("dispatches") - d_before,
        "batches": dstats.get("batches", 0),
        "batched_requests": dstats.get("batched_requests", 0),
        "avg_occupancy": dstats.get("avg_occupancy", 0.0),
        "deadline_misses": misses[0],
        "cancels": (disp.counter("cancel_requests")
                    + disp.counter("watchdog_timeouts")) - x_before,
        "recovery_count": disp.counter("recoveries") - r_before,
        "tiles_replayed": disp.counter("tiles_replayed") - tr_before,
        "recovery_ms": disp.counter("recovery_wall_ms") - rw_before,
        "tenant": "all",
        "tenant_qps": round(len(all_lats) / max(wall, 1e-9), 1),
        "tenant_p50_ms": round(_pct(all_lats, 0.50), 3),
        "tenant_p99_ms": round(_pct(all_lats, 0.99), 3),
        "tenant_queue_depth": dstats.get("max_depth", 0),
        "fairness_index": round(fidx, 4),
        "acked_lost": 0,  # the --kill-at column; a live run loses nothing
        # non-CSV extras for programmatic callers
        "_backpressure": rejects[0],
    }
    # server-side percentiles + stage time shares (obs registry): the
    # engine's own statement_seconds histogram, immune to client-side
    # queuing in the bench drivers
    reg = session.stmt_log.registry
    sh = reg.hist("statement_seconds") or {}
    shares, spans = _stage_shares(reg)
    out["srv_p50_ms"] = round(sh.get("p50", 0.0) * 1000, 3)
    out["srv_p95_ms"] = round(sh.get("p95", 0.0) * 1000, 3)
    out["srv_p99_ms"] = round(sh.get("p99", 0.0) * 1000, 3)
    for col in ("queue_wait_share", "compile_share", "launch_share",
                "render_share"):
        out[col] = shares.get(col, 0.0)
    out["trace_spans"] = spans
    # capacity & forensics columns (ISSUE 12): flight captures over the
    # run, skew alarms from the motion telemetry, and the peak
    # per-statement device-byte estimate (high-water gauge)
    out["flight_captures"] = disp.counter("flight_captures") - fl_before
    out["skew_events"] = disp.counter("skew_events") - sk_before
    peak = reg.snapshot()["gauges"].get("stmt_device_bytes_peak", 0.0)
    out["peak_stmt_mb"] = round(peak / (1 << 20), 3)
    # online-topology chaos columns (ISSUE 13)
    out["cutover_ms"] = round(cutover_ms[0], 2)
    out["moved_rows"] = disp.counter("topo_moved_rows") - mr_before
    out["epoch_flips"] = disp.counter("epoch_flips") - ef_before
    # HBM buffer-pool columns (ISSUE 16): hit rate over the run's pool
    # lookups and the host decode count — under --mix hotcold the hot
    # set's repeats stop decoding once admitted, so host_decodes
    # tracks the cold set (plus the hot set's single admission pass)
    bh = disp.counter("bufpool_hits") - bh_before
    bm = disp.counter("bufpool_misses") - bm_before
    out["bufpool_hit_rate"] = round(bh / (bh + bm), 4) if bh + bm else 0.0
    out["host_decodes"] = disp.counter("host_decodes") - hd_before
    # feedback-driven re-optimization columns (ISSUE 17): mid-statement
    # adaptive replans taken over the window, and capacity rungs the
    # learned sketches priced DOWN from the static estimate (the wire /
    # padding saving the feedback loop bought on repeat statements)
    out["adaptive_replans"] = (disp.counter("adaptive_replans")
                               - ar_before)
    out["rung_downgrades"] = (disp.counter("rung_downgrades")
                              - rd_before)
    # write-plane columns (ISSUE 18): appends/s the ingest buffers
    # accepted, p95 group-flush commit latency, compaction chunks
    # folded during the window, and a LIVE end-of-run census of the
    # bounded invariant (worst per-table delta-partition count, read
    # from the manifests rather than the compactor's cached gauge)
    out["ingest_qps"] = round(
        (disp.counter("ingest_appends") - ia_before) / max(wall, 1e-9), 1)
    fh = reg.hist("ingest_flush_seconds") or {}
    out["flush_ms_p95"] = round(fh.get("p95", 0.0) * 1000, 3)
    out["compact_chunks"] = disp.counter("compact_chunks") - cc_before
    # windowed tile dispatch columns (ISSUE 20)
    out["tile_deferred_overflows"] = (
        disp.counter("tile_deferred_overflows") - do_before)
    out["tile_window_replays"] = (
        disp.counter("tile_window_replays") - wr_before)
    dmax = 0
    if session.store is not None and mix == "readwrite":
        from cloudberry_tpu.storage.compact import delta_parts

        rpp = getattr(session.store, "rows_per_partition", 1 << 20)
        tf = session.config.compact.target_fill
        for name in session.store.table_names():
            man = session.store.read_manifest(name)
            if man["schema"] is not None:
                dmax = max(dmax, delta_parts(man, rpp, tf))
    out["delta_parts_max"] = dmax
    out["_read_qps"] = round(reads[0] / max(wall, 1e-9), 1)
    if mix == "hotcold":
        out.update(_hotcold_probe(session))
    _cleanup()
    if trace_sample and trace_out:
        from cloudberry_tpu.obs.trace import chrome_trace

        with open(trace_out, "w") as fh:
            json.dump(chrome_trace(session.stmt_log.traces(512)), fh)
        print(f"# trace written to {trace_out} "
              f"({spans} sampled statements)", file=sys.stderr)
    if tenant_names:
        # one CSV row per tenant, riding the aggregate's shared columns
        trs = []
        for name in tenant_names:
            tl = sorted(lat_map.get(name, []))
            tr = dict(out)
            tr.update({
                "tenant": name,
                "tenant_qps": round(len(tl) / max(wall, 1e-9), 1),
                "tenant_p50_ms": round(_pct(tl, 0.50), 3),
                "tenant_p99_ms": round(_pct(tl, 0.99), 3),
                "tenant_queue_depth": tsnap.get(name, {}).get(
                    "max_depth", 0),
            })
            trs.append(tr)
        out["_tenants"] = trs
    return out


def run_killat(seam: str, hit: int | None = None) -> dict:
    """--kill-at: one process-kill torture pass as a bench row. The
    heavy lifting (server subprocess, CBTPU_INJECT arming, restart,
    wire verify, fsck) is tools/crash_torture.py's run_seam; this
    wrapper shapes the verdict into the serving CSV so crash recovery
    rides the same dashboards as QPS. acked_lost != 0 or any problem
    is a FAILURE, surfaced both in the row and on stderr."""
    from tools.crash_torture import MATRIX_SEAMS, run_seam

    known = dict(MATRIX_SEAMS)
    if hit is None:
        hit = known.get(seam, 6)
    rec = run_seam(seam, hit=hit)
    row = {k: 0 for k in CSV_HEADER.split(",")}
    row.update({
        "mode": "killat", "mix": seam, "clients": 1,
        "duration_s": 0.0, "requests": rec["acked_inserts"],
        "qps": 0.0, "p50_ms": 0.0, "p99_ms": 0.0,
        "avg_occupancy": 0.0, "fairness_index": 1.0, "tenant": "all",
        "recovery_count": 1 if rec["fired"] else 0,
        "recovery_ms": rec["recovery_ms"] or 0.0,
        "acked_lost": rec["acked_lost"],
        # non-CSV extras for programmatic callers / tests
        "_torture": rec,
    })
    for p in rec["problems"]:
        print(f"# kill-at {seam}@{hit}: {p}", file=sys.stderr)
    if not rec["problems"]:
        print(f"# kill-at {seam}@{hit}: clean — exit=137, "
              f"acked={rec['acked_inserts']}, acked_lost=0, "
              f"recovery={rec['recovery_ms']}ms, fsck clean",
              file=sys.stderr)
    return row


def _parse_at(spec):
    """'T:N' → (T seconds into the run, N target segments), or None."""
    if not spec:
        return None
    t, _, n = str(spec).partition(":")
    return (float(t), int(n))


def csv_row(r: dict) -> str:
    return ",".join(str(r[k]) for k in CSV_HEADER.split(","))


def main(argv=None) -> list[dict]:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--mode", default="both",
                    choices=["both", "direct", "batched"])
    ap.add_argument("--mix", default="point",
                    choices=["point", "q6", "mixed", "spill",
                             "coldscan", "hotcold", "readwrite"])
    ap.add_argument("--clients", type=int, default=16)
    ap.add_argument("--duration", type=float, default=5.0)
    ap.add_argument("--rows", type=int, default=200_000)
    ap.add_argument("--tick-s", type=float, default=0.002)
    ap.add_argument("--max-batch", type=int, default=32)
    ap.add_argument("--cancel-mix", type=float, default=0.0,
                    help="fraction of requests carrying a tight "
                         "per-request deadline (lifecycle workload)")
    ap.add_argument("--deadline-s", type=float, default=0.005,
                    help="the tight deadline used by --cancel-mix")
    ap.add_argument("--chaos", type=float, default=0.0,
                    help="per-hit device-loss probability armed on the "
                         "dispatch/tile seams (recovery workload; pair "
                         "with --mix spill)")
    ap.add_argument("--tenants", default=None,
                    help="tenant spec 'name:weight[:conc[:queue]],...' "
                         "— enables per-tenant fair scheduling and the "
                         "per-tenant CSV rows (e.g. gold:3,silver:1)")
    ap.add_argument("--server-core", default="async",
                    choices=["async", "threaded"],
                    help="serving transport: the event-loop front end "
                         "(default) or legacy thread-per-connection")
    ap.add_argument("--driver-threads", type=int, default=16,
                    help="selector driver threads multiplexing the "
                         "simulated clients (large --clients runs)")
    ap.add_argument("--aging-s", type=float, default=None,
                    help="tenancy starvation bound override (waits past "
                         "it are served oldest-first, trading weight "
                         "proportionality for bounded p99)")
    ap.add_argument("--trace-sample", type=int, default=0,
                    help="sample every Nth statement's span tree into "
                         "--trace-out (perfetto-loadable) and report "
                         "per-stage time-share columns")
    ap.add_argument("--trace-out", default="serve_trace.json",
                    help="chrome-trace output path for --trace-sample")
    ap.add_argument("--slow-ms", type=float, default=None,
                    help="flight-recorder threshold for the run "
                         "(config.obs.slow_ms): statements slower than "
                         "this capture debug bundles, counted in the "
                         "flight_captures CSV column")
    ap.add_argument("--segments", type=int, default=1,
                    help="segment count the serving session starts at "
                         "(online resizes move FROM here)")
    ap.add_argument("--expand-at", default=None, metavar="T:N",
                    help="land an epoch-versioned online expand to N "
                         "segments T seconds into the measured window "
                         "(needs N visible devices; cutover_ms / "
                         "moved_rows / epoch_flips CSV columns)")
    ap.add_argument("--shrink-at", default=None, metavar="T:N",
                    help="same, shrinking to N segments")
    ap.add_argument("--kill-at", default=None, metavar="SEAM",
                    help="crash-recovery bench: launch a real server "
                         "subprocess, kill it (os._exit) at this armed "
                         "durability seam mid-workload, restart, and "
                         "verify — emits one CSV row whose recovery_ms "
                         "is restart-to-first-answer and whose "
                         "acked_lost MUST be 0 (see "
                         "tools/crash_torture.py MATRIX_SEAMS)")
    ap.add_argument("--kill-hit", type=int, default=None,
                    help="fire --kill-at on the Nth seam hit "
                         "(default: the torture matrix's)")
    ap.add_argument("--no-compact", action="store_true",
                    help="readwrite baseline: same append share with "
                         "the compaction service off (the A/B for the "
                         "read-QPS-holds-under-compaction claim)")
    ap.add_argument("--csv", default=None,
                    help="append CSV rows to this file")
    args = ap.parse_args(argv)

    if args.clients > 256:
        # 1k+ simulated clients need 2x that many fds in ONE process
        # (both socket ends live here); lift the soft limit to the hard
        try:
            import resource as _res

            soft, hard = _res.getrlimit(_res.RLIMIT_NOFILE)
            want = min(hard, max(soft, args.clients * 4 + 256))
            if want > soft:
                _res.setrlimit(_res.RLIMIT_NOFILE, (want, hard))
        except (ImportError, ValueError, OSError):
            pass
    if args.kill_at:
        r = run_killat(args.kill_at, args.kill_hit)
        print(CSV_HEADER)
        print(csv_row(r), flush=True)
        if args.csv:
            new = not os.path.exists(args.csv)
            with open(args.csv, "a") as fh:
                if new:
                    fh.write(CSV_HEADER + "\n")
                fh.write(csv_row(r) + "\n")
        return [r]
    tenants = parse_tenantspec(args.tenants, args.clients) \
        if args.tenants else None
    modes = ["direct", "batched"] if args.mode == "both" else [args.mode]
    out = []
    rows_out = []
    print(CSV_HEADER)
    for mode in modes:
        r = run_mode(mode, args.mix, args.clients, args.duration,
                     args.rows, args.tick_s, args.max_batch,
                     cancel_mix=args.cancel_mix,
                     deadline_s=args.deadline_s, chaos=args.chaos,
                     tenants=tenants, server_core=args.server_core,
                     driver_threads=args.driver_threads,
                     aging_s=args.aging_s,
                     trace_sample=args.trace_sample,
                     trace_out=args.trace_out,
                     slow_ms=args.slow_ms, segments=args.segments,
                     expand_at=_parse_at(args.expand_at),
                     shrink_at=_parse_at(args.shrink_at),
                     compact_off=args.no_compact)
        out.append(r)
        rows_out.append(r)
        rows_out.extend(r.get("_tenants", ()))
        for rr in [r] + list(r.get("_tenants", ())):
            print(csv_row(rr), flush=True)
        if args.mix == "hotcold":
            print(f"# hotcold[{mode}]: hot {r['_hot_rows_per_s']} rows/s"
                  f" ({r['_hot_host_decodes']} host decodes) vs cold "
                  f"{r['_cold_rows_per_s']} rows/s "
                  f"({r['_cold_host_decodes']} host decodes); "
                  f"run hit rate {r['bufpool_hit_rate']}",
                  file=sys.stderr)
    if args.csv:
        new = not os.path.exists(args.csv)
        with open(args.csv, "a") as fh:
            if new:
                fh.write(CSV_HEADER + "\n")
            for r in rows_out:
                fh.write(csv_row(r) + "\n")
    if len(out) == 2:
        base, batched = out[0]["qps"], out[1]["qps"]
        if base > 0:
            print(f"# batched/direct QPS: {batched / base:.2f}x",
                  file=sys.stderr)
    return out


if __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    main()
