"""Closed-loop serving benchmark — QPS/latency for the micro-batch
dispatcher vs one-at-a-time dispatch (the ISSUE-3 acceptance harness).

N client threads hammer a Server over the wire protocol with a statement
mix; each mode runs the SAME closed loop and the CSV rows make the
comparison direct:

    mode,mix,clients,duration_s,requests,qps,p50_ms,p99_ms,compiles,\
dispatches,batches,batched_requests,avg_occupancy,deadline_misses,\
cancels,recovery_count,tiles_replayed,recovery_ms

- ``direct``  — dispatcher off: every request is its own parse→(generic
  rebind)→launch through the shared session.
- ``batched`` — dispatcher on (config.sched.enabled): same-skeleton
  requests coalesce per tick into one stacked vmapped launch.

Mixes:
- ``point`` — repeated point lookups with rotating literals
  (``SELECT k, v, w FROM pts WHERE k = <r>``): the prepared-statement
  serving shape; generic plans make it compile-free, the dispatcher makes
  it launch-amortized.
- ``q6``    — a parameterized TPC-H-Q6-shaped aggregate over a synthetic
  lineitem slice with rotating predicate literals.
- ``mixed`` — 80% point / 20% q6.

Runs on CPU (JAX_PLATFORMS=cpu) for CI smoke; on real hardware the launch
amortization grows with dispatch overhead. Usage:

    python tools/serve_bench.py --mode both --mix point --clients 8 \
        --duration 5 --csv out.csv
"""

from __future__ import annotations

import argparse
import os
import sys
import threading
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

CSV_HEADER = ("mode,mix,clients,duration_s,requests,qps,p50_ms,p99_ms,"
              "compiles,dispatches,batches,batched_requests,avg_occupancy,"
              "deadline_misses,cancels,recovery_count,tiles_replayed,"
              "recovery_ms")


def build_session(mode: str, rows: int, tick_s: float, max_batch: int,
                  mix: str = "point", chaos: float = 0.0):
    import numpy as np

    import cloudberry_tpu as cb
    from cloudberry_tpu.config import Config

    over = {
        "sched.enabled": mode == "batched",
        "sched.tick_s": tick_s,
        "sched.max_batch": max_batch,
    }
    if mix == "spill":
        # the chaos workload streams tiles: shrink the budget so the li
        # aggregate runs through the tiled (checkpointable) path
        over["resource.query_mem_bytes"] = 1 << 20
    if chaos > 0:
        # probabilistic device loss compounds per tile: give recovery
        # more re-dispatches than the default flap allowance
        over["health.retries"] = 4
    cfg = Config().with_overrides(**over)
    s = cb.Session(cfg)
    s.sql("create table pts (k bigint, v bigint, w double) "
          "distributed by (k)")
    t = s.catalog.table("pts")
    t.set_data({
        "k": np.arange(rows, dtype=np.int64),
        "v": (np.arange(rows, dtype=np.int64) * 7) % 1000,
        "w": np.arange(rows, dtype=np.float64) * 0.5,
    }, {})
    s.sql("create table li (qty decimal(2), price decimal(2), "
          "disc decimal(2), sd date)")
    rng = np.random.default_rng(11)
    m = max(rows // 2, 1024)
    s.catalog.table("li").set_data({
        "qty": rng.integers(1, 5000, m).astype(np.int64),
        "price": rng.integers(100, 10000, m).astype(np.int64),
        "disc": rng.integers(0, 11, m).astype(np.int64),
        "sd": rng.integers(8000, 12000, m).astype(np.int32),
    }, {})
    return s


def _point_sql(i: int, rows: int) -> str:
    return f"select k, v, w from pts where k = {(i * 2654435761) % rows}"


def _q6_sql(i: int) -> str:
    lo = 1 + (i % 5)
    return ("select sum(price * disc) as rev from li "
            f"where disc between 0.0{lo} and 0.0{lo + 4} "
            f"and qty < {20 + (i % 7)}.0")


def _spill_sql(i: int) -> str:
    # a tiled (out-of-core) aggregate with rotating literals: under the
    # shrunken spill-mix budget this statement streams tiles through the
    # checkpoint seams — the --chaos recovery workload
    return ("select sum(price) as sp, count(*) as c from li "
            f"where qty < {4000 + (i % 50)}.0")


def _mix_sql(mix: str, i: int, rows: int) -> str:
    if mix == "point":
        return _point_sql(i, rows)
    if mix == "q6":
        return _q6_sql(i)
    if mix == "spill":
        return _spill_sql(i)
    return _q6_sql(i) if i % 5 == 4 else _point_sql(i, rows)


def run_mode(mode: str, mix: str, clients: int, duration_s: float,
             rows: int, tick_s: float, max_batch: int,
             cancel_mix: float = 0.0, deadline_s: float = 0.005,
             chaos: float = 0.0) -> dict:
    """One closed-loop run; returns the CSV row fields.

    ``cancel_mix``: fraction of requests carrying a TIGHT per-request
    deadline (``deadline_s``) — the statement-lifecycle workload. Those
    that miss fail with the retryable timeout taxonomy (StatementTimeout
    / SchedDeadline) and count as ``deadline_misses``, not errors; the
    ``cancels`` column reports the engine's cancellation counters
    (cancel verb + watchdog) over the run.

    ``chaos``: per-hit device-loss probability armed on the dispatch and
    tile seams (utils/faultinject probabilistic arms) — the recovery
    workload. The recovery_count / tiles_replayed / recovery_ms columns
    report what the engine's checkpointed re-execution actually did;
    pair with ``--mix spill`` so statements stream tiles worth
    resuming."""
    from cloudberry_tpu.serve import Client, Server, ServerError
    from cloudberry_tpu.utils import faultinject as FI

    session = build_session(mode, rows, tick_s, max_batch,
                            mix=mix, chaos=chaos)
    # warm the compile caches OUTSIDE the measured window: the bench
    # compares steady-state dispatch, not first-compile latency
    session.sql(_point_sql(0, rows))
    session.sql(_q6_sql(0))
    if mix == "spill":
        session.sql(_spill_sql(0))
    c_before = session.stmt_log.counter("compiles")
    d_before = session.stmt_log.counter("dispatches")
    x_before = (session.stmt_log.counter("cancel_requests")
                + session.stmt_log.counter("watchdog_timeouts"))
    r_before = session.stmt_log.counter("recoveries")
    tr_before = session.stmt_log.counter("tiles_replayed")
    rw_before = session.stmt_log.counter("recovery_wall_ms")

    _MISS_ETYPES = ("StatementTimeout", "StatementCancelled",
                    "SchedDeadline")
    # a chaos run's residual losses (retries exhausted under the armed
    # device-loss rate) are the workload working, not bench failures
    _CHAOS_ETYPES = ("InjectedFault", "XlaRuntimeError")
    lats: list[float] = []
    misses = [0]
    lat_lock = threading.Lock()
    errors: list[str] = []
    stop_at = [0.0]
    stride = max(1, int(round(1.0 / cancel_mix))) if cancel_mix else 0

    def worker(wid: int):
        lat_local = []
        miss_local = 0
        try:
            with Client(srv.host, srv.port) as c:
                i = wid * 100_003
                while time.monotonic() < stop_at[0]:
                    sql = _mix_sql(mix, i, rows)
                    dl = deadline_s if stride and i % stride == 0 else None
                    i += 1
                    t0 = time.monotonic()
                    try:
                        c.sql(sql, deadline_s=dl)
                    except ServerError as e:
                        # a deadlined request missing its deadline is the
                        # workload working, not a bench failure
                        if dl is not None and e.etype in _MISS_ETYPES:
                            miss_local += 1
                        elif chaos and e.etype in _CHAOS_ETYPES:
                            pass
                        else:
                            raise
                    lat_local.append(time.monotonic() - t0)
        except Exception as e:  # pragma: no cover - surfaced in result
            errors.append(f"{type(e).__name__}: {e}")
        with lat_lock:
            lats.extend(lat_local)
            misses[0] += miss_local

    if chaos > 0:
        FI.inject_fault("tile_device_lost", "error", p=chaos, seed=1234)
        FI.inject_fault("exec_device_lost", "error", p=chaos, seed=4321)
    with Server(session=session) as srv:
        stop_at[0] = time.monotonic() + duration_s
        threads = [threading.Thread(target=worker, args=(i,))
                   for i in range(clients)]
        t_start = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=duration_s + 120)
        wall = time.monotonic() - t_start
        disp = session.stmt_log
        dsnap = getattr(session, "_dispatcher", None)
        dstats = dsnap.snapshot() if dsnap is not None else {}
    if chaos > 0:
        FI.reset_fault("tile_device_lost")
        FI.reset_fault("exec_device_lost")
    if errors:
        raise RuntimeError(f"bench clients failed: {errors[:3]}")
    lats.sort()

    def pct(p: float) -> float:
        if not lats:
            return 0.0
        return lats[min(len(lats) - 1, int(p * len(lats)))] * 1000

    return {
        "mode": mode, "mix": mix, "clients": clients,
        "duration_s": round(wall, 2), "requests": len(lats),
        "qps": round(len(lats) / max(wall, 1e-9), 1),
        "p50_ms": round(pct(0.50), 3), "p99_ms": round(pct(0.99), 3),
        "compiles": disp.counter("compiles") - c_before,
        "dispatches": disp.counter("dispatches") - d_before,
        "batches": dstats.get("batches", 0),
        "batched_requests": dstats.get("batched_requests", 0),
        "avg_occupancy": dstats.get("avg_occupancy", 0.0),
        "deadline_misses": misses[0],
        "cancels": (disp.counter("cancel_requests")
                    + disp.counter("watchdog_timeouts")) - x_before,
        "recovery_count": disp.counter("recoveries") - r_before,
        "tiles_replayed": disp.counter("tiles_replayed") - tr_before,
        "recovery_ms": disp.counter("recovery_wall_ms") - rw_before,
    }


def csv_row(r: dict) -> str:
    return ",".join(str(r[k]) for k in CSV_HEADER.split(","))


def main(argv=None) -> list[dict]:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--mode", default="both",
                    choices=["both", "direct", "batched"])
    ap.add_argument("--mix", default="point",
                    choices=["point", "q6", "mixed", "spill"])
    ap.add_argument("--clients", type=int, default=16)
    ap.add_argument("--duration", type=float, default=5.0)
    ap.add_argument("--rows", type=int, default=200_000)
    ap.add_argument("--tick-s", type=float, default=0.002)
    ap.add_argument("--max-batch", type=int, default=32)
    ap.add_argument("--cancel-mix", type=float, default=0.0,
                    help="fraction of requests carrying a tight "
                         "per-request deadline (lifecycle workload)")
    ap.add_argument("--deadline-s", type=float, default=0.005,
                    help="the tight deadline used by --cancel-mix")
    ap.add_argument("--chaos", type=float, default=0.0,
                    help="per-hit device-loss probability armed on the "
                         "dispatch/tile seams (recovery workload; pair "
                         "with --mix spill)")
    ap.add_argument("--csv", default=None,
                    help="append CSV rows to this file")
    args = ap.parse_args(argv)

    modes = ["direct", "batched"] if args.mode == "both" else [args.mode]
    out = []
    print(CSV_HEADER)
    for mode in modes:
        r = run_mode(mode, args.mix, args.clients, args.duration,
                     args.rows, args.tick_s, args.max_batch,
                     cancel_mix=args.cancel_mix,
                     deadline_s=args.deadline_s, chaos=args.chaos)
        out.append(r)
        print(csv_row(r), flush=True)
    if args.csv:
        new = not os.path.exists(args.csv)
        with open(args.csv, "a") as fh:
            if new:
                fh.write(CSV_HEADER + "\n")
            for r in out:
                fh.write(csv_row(r) + "\n")
    if len(out) == 2:
        base, batched = out[0]["qps"], out[1]["qps"]
        if base > 0:
            print(f"# batched/direct QPS: {batched / base:.2f}x",
                  file=sys.stderr)
    return out


if __name__ == "__main__":
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    main()
