#!/usr/bin/env python
"""CI gate runner for graftlint — findings as one JSON document.

``python tools/lint_gate.py [paths...]`` runs the analyzer (default: the
cloudberry_tpu package) and prints a single JSON object:

    {"ok": true|false,
     "findings": [...unsuppressed, file/line/rule/message...],
     "rule_counts": {"lock-unguarded": 2, ...},
     "suppressions": N,
     "suppression_sites": [{"file", "line", "rule", "justification"}],
     "files": N}

Exit code mirrors ``python -m cloudberry_tpu.lint``: 0 clean, 1 findings.
The bench harness embeds the same counts as its "lint" record
(bench.py lint_context) so rule/suppression drift shows up in the bench
trajectory next to the perf numbers.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def gate_record(paths=None) -> dict:
    """The machine-readable gate document (shared with bench.py)."""
    import cloudberry_tpu
    from cloudberry_tpu.lint import run_lint

    if not paths:
        paths = [os.path.dirname(os.path.abspath(
            cloudberry_tpu.__file__))]
    result = run_lint(paths)
    sup = [{"file": f.file, "line": f.line, "rule": f.rule,
            "justification": f.justification}
           for f in result.suppressed]
    return {
        "ok": not result.unsuppressed,
        "findings": [f.as_dict() for f in result.unsuppressed],
        "rule_counts": result.rule_counts(),
        "suppressions": len(result.suppressed),
        "suppression_sites": sup,
        "files": len(result.modules),
    }


def main() -> int:
    rec = gate_record([p for p in sys.argv[1:] if not p.startswith("-")])
    print(json.dumps(rec, indent=1))
    return 0 if rec["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
