#!/usr/bin/env python
"""CI gate runner for graftlint — findings as one JSON document.

``python tools/lint_gate.py [paths...]`` runs the analyzer (default: the
cloudberry_tpu package) and prints a single JSON object:

    {"ok": true|false,
     "findings": [...unsuppressed, file/line/rule/message...],
     "rule_counts": {"lock-unguarded": 2, ...},
     "suppressions": N,
     "suppression_sites": [{"file", "line", "rule", "justification"}],
     "files": N}

``--plans`` additionally runs the planck plan verifier (plan/verify.py)
over the whole TPC-H + TPC-DS golden corpus at 1 and 8 segments and
merges a "plans" record ({"plans", "nodes", "rules_hit", "findings",
"wall_s"}) — one CI gate for the Python invariants AND the plan-IR
invariants.

Exit code mirrors ``python -m cloudberry_tpu.lint``: 0 clean, 1 findings
(from either gate), 2 usage/setup error. The bench harness embeds the
same counts as its "lint" / "planverify" records (bench.py
lint_context / planverify_context) so rule/suppression/plan drift shows
up in the bench trajectory next to the perf numbers.
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def gate_record(paths=None) -> dict:
    """The machine-readable gate document (shared with bench.py)."""
    import cloudberry_tpu
    from cloudberry_tpu.lint import run_lint

    if not paths:
        paths = [os.path.dirname(os.path.abspath(
            cloudberry_tpu.__file__))]
    result = run_lint(paths)
    sup = [{"file": f.file, "line": f.line, "rule": f.rule,
            "justification": f.justification}
           for f in result.suppressed]
    return {
        "ok": not result.unsuppressed,
        "findings": [f.as_dict() for f in result.unsuppressed],
        "rule_counts": result.rule_counts(),
        "suppressions": len(result.suppressed),
        "suppression_sites": sup,
        "files": len(result.modules),
    }


def plans_record() -> dict:
    """Golden-corpus plan verification (shared with bench.py's
    planverify record): every TPC-H + TPC-DS plan at 1 and 8 segments
    through plan/verify.py."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    from tools.golden_plans import verify_corpus

    rec = verify_corpus()
    rec["ok"] = not rec["findings"]
    rec["rules_hit"] = len(rec["rules_hit"])
    rec["wall_s"] = round(rec["wall_s"], 3)
    return rec


def main() -> int:
    args = sys.argv[1:]
    rec = gate_record([p for p in args if not p.startswith("-")])
    if "--plans" in args:
        try:
            rec["plans"] = plans_record()
        except Exception as e:
            print(f"plan verification did not run: "
                  f"{type(e).__name__}: {e}", file=sys.stderr)
            return 2
        rec["ok"] = rec["ok"] and rec["plans"]["ok"]
    print(json.dumps(rec, indent=1))
    return 0 if rec["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
