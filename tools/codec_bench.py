"""Standalone native-codec benchmark — the ic_bench.c / pax_gbench analog:
component performance measured with no cluster or engine involved.

Usage: python tools/codec_bench.py [n_values]
"""

from __future__ import annotations

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from cloudberry_tpu import native  # noqa: E402


def bench(name, fn, reps=5):
    best = float("inf")
    for _ in range(reps):
        t = time.time()
        out = fn()
        best = min(best, time.time() - t)
    return best, out


def main():
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 10_000_000
    lib = native.load_native()
    print(f"native codec: {'loaded' if lib else 'UNAVAILABLE (fallback)'}")
    keys = np.arange(n, dtype=np.int64) * 7 // 3   # sorted-ish keys
    rng = np.random.default_rng(0)
    mixed = keys + rng.integers(-100, 100, n)

    for label, arr in [("sorted keys", keys), ("near-sorted", mixed)]:
        t_enc, buf = bench(f"enc {label}", lambda: native.dvarint_encode(arr))
        t_dec, out = bench(f"dec {label}",
                           lambda: native.dvarint_decode(buf, n))
        assert (out == arr).all()
        mb = arr.nbytes / 1e6
        print(f"{label:12s}: encode {mb / t_enc:8.0f} MB/s   "
              f"decode {mb / t_dec:8.0f} MB/s   "
              f"ratio {arr.nbytes / len(buf):5.1f}x")

    lines = b"\n".join(
        b"%d|name%d|%d.%02d" % (i, i, i % 1000, i % 100)
        for i in range(min(n, 2_000_000)))
    t_csv, ids = bench("csv int64",
                       lambda: native.parse_int64_column(lines, 0))
    print(f"csv int64   : parse  {len(lines) / 1e6 / t_csv:8.0f} MB/s   "
          f"({len(ids)} rows)")


if __name__ == "__main__":
    main()
